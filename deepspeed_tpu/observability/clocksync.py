"""Per-channel clock synchronization for the cross-process fleet.

Request spans, load-report timestamps, and flight events all carry
wall-clock stamps from the process that produced them. On one host with
one clock that is exact; the moment the fleet leaves localhost (or a
worker's NTP steps its clock mid-run) the timelines stop being
comparable — a worker 250 ms ahead of the router renders its PREFILL
span *before* the ROUTE decision that caused it. This module closes
that gap with the NTP client discipline, scaled down to one estimator
per transport channel:

* :func:`wall_time` is the fleet's observability clock: ``time.time()``
  plus the ``DSTPU_CLOCK_SKEW_S`` env offset (read per call). Production
  code never sets the env var, so it IS ``time.time()``; chaos drills
  and the obs-fleet bench set it per process to inject a known skew and
  then assert the estimator recovers it.
* :class:`ClockSyncEstimator` consumes ping/pong round trips
  (``t0``: local send, ``t1``: peer receive, ``t2``: peer reply,
  ``t3``: local receive — all on :func:`wall_time`) injected by the
  transport layer (serving/transport/channel.py intercepts
  ``clock_ping``/``clock_pong`` messages below the message protocol, so
  every channel owner gets clock sync without protocol changes). Per
  sample: ``offset = ((t1 - t0) + (t2 - t3)) / 2`` (peer minus local),
  ``rtt = (t3 - t0) - (t2 - t1)``. The estimate is the **median offset
  of the K lowest-RTT samples** in a bounded window — the standard
  defense against queueing-delayed samples, which is exactly what a
  chaos ``net_delay_ms`` arm or a worker blocked in a multi-second JIT
  compile produces.
* The **uncertainty bound** is ``best_rtt / 2`` (the irreducible
  one-way-delay ambiguity: an adversarial asymmetric path can hide up
  to half the round trip) plus the dispersion of the voting offsets —
  honest even under asymmetric injected delay, where the point estimate
  is biased by up to half the asymmetry.
* **Drift** is an EWMA of offset change per second between re-sync
  rounds: a worker whose clock *rates* differently (not just steps)
  shows a nonzero drift long before the offset outgrows the bound.

Everything here is host-side, jax-free, and import-cheap — the channel
layer imports it on the first clock message, not at module load.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

SKEW_ENV = "DSTPU_CLOCK_SKEW_S"


def wall_time() -> float:
    """The observability wall clock: ``time.time()`` plus the injected
    per-process skew (``DSTPU_CLOCK_SKEW_S``, read per call so a test
    can *step* the clock mid-run). With the env unset this is exactly
    ``time.time()`` — zero-cost in the only path production takes."""
    skew = os.environ.get(SKEW_ENV)
    if not skew:
        return time.time()
    try:
        return time.time() + float(skew)
    except ValueError:
        return time.time()


class ClockSyncEstimator:
    """NTP-style offset estimator for one channel's peer.

    ``offset_s`` is *peer minus local*: a peer timestamp rebases into
    local time as ``local_ts = peer_ts - offset_s``. ``synced`` turns
    True after ``min_samples`` round trips; until then consumers must
    fall back to the raw timestamps (the bit-exact pre-clocksync
    behavior).

    Thread-safety: ``add_round_trip`` runs on the channel's receive
    thread while readers (router/supervisor) poll from theirs — one
    lock covers the sample window and the cached estimate.
    """

    def __init__(self, k: int = 5, window: int = 32,
                 min_samples: int = 3, drift_alpha: float = 0.2):
        self.k = max(1, int(k))
        self.window = max(self.k, int(window))
        self.min_samples = max(1, int(min_samples))
        self.drift_alpha = float(drift_alpha)
        self._lock = threading.Lock()
        # (rtt_s, offset_s, t3) tuples, newest last, bounded by window
        self._samples: List[Tuple[float, float, float]] = []
        self._offset = 0.0
        self._uncertainty = float("inf")
        self._drift = 0.0  # seconds of offset change per second
        self._last_estimate: Optional[Tuple[float, float]] = None
        self.n_samples = 0
        self.last_sync_mono = 0.0  # re-sync cadence (monotonic)

    # -- ingest --------------------------------------------------------
    def add_round_trip(self, t0: float, t1: float, t2: float,
                       t3: float) -> None:
        """One ping/pong sample. ``t0``/``t3`` are local send/receive
        stamps; ``t1``/``t2`` are the peer's receive/reply stamps. A
        nonsensical sample (negative RTT — a stepped clock mid-flight)
        is dropped rather than poisoning the window."""
        rtt = (t3 - t0) - (t2 - t1)
        if rtt < 0.0:
            return
        offset = ((t1 - t0) + (t2 - t3)) / 2.0
        with self._lock:
            self._samples.append((rtt, offset, t3))
            if len(self._samples) > self.window:
                self._samples = self._samples[-self.window:]
            self.n_samples += 1
            self.last_sync_mono = time.monotonic()
            self._recompute(t3)

    def _recompute(self, now: float) -> None:
        """Re-derive offset/uncertainty/drift from the window. Caller
        holds the lock."""
        if len(self._samples) < self.min_samples:
            return
        best = sorted(self._samples)[:self.k]  # lowest RTT first
        offsets = sorted(o for _, o, _ in best)
        mid = len(offsets) // 2
        est = (offsets[mid] if len(offsets) % 2
               else (offsets[mid - 1] + offsets[mid]) / 2.0)
        dispersion = max(offsets) - min(offsets)
        unc = best[0][0] / 2.0 + dispersion
        prev = self._last_estimate
        if prev is not None:
            dt = now - prev[1]
            if dt > 1e-3:
                rate = (est - prev[0]) / dt
                self._drift = (self.drift_alpha * rate
                               + (1.0 - self.drift_alpha) * self._drift)
        self._last_estimate = (est, now)
        self._offset = est
        self._uncertainty = unc

    def reset(self) -> None:
        """Drop the window (a stepped peer clock: re-converge from
        scratch rather than median across two clock regimes)."""
        with self._lock:
            self._samples.clear()
            self._offset = 0.0
            self._uncertainty = float("inf")
            self._drift = 0.0
            self._last_estimate = None

    # -- readout -------------------------------------------------------
    @property
    def synced(self) -> bool:
        with self._lock:
            return len(self._samples) >= self.min_samples

    @property
    def offset_s(self) -> float:
        """Peer clock minus local clock, in seconds (0.0 until
        synced)."""
        with self._lock:
            return self._offset if len(
                self._samples) >= self.min_samples else 0.0

    @property
    def uncertainty_s(self) -> float:
        with self._lock:
            return (self._uncertainty
                    if len(self._samples) >= self.min_samples
                    else float("inf"))

    @property
    def drift(self) -> float:
        """EWMA of offset change per second across re-sync rounds."""
        with self._lock:
            return self._drift

    def rebase(self, peer_ts: float) -> float:
        """A peer wall-clock timestamp in local time (identity until
        synced)."""
        return peer_ts - self.offset_s

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            synced = len(self._samples) >= self.min_samples
            return {
                "synced": synced,
                "offset_ms": round(self._offset * 1e3, 4) if synced
                else None,
                "uncertainty_ms": round(self._uncertainty * 1e3, 4)
                if synced else None,
                "drift_ppm": round(self._drift * 1e6, 3),
                "samples": self.n_samples,
                "window": len(self._samples),
            }
