"""Pipeline parallelism: microbatched stage pipeline over the pp mesh axis.

Reference: runtime/pipe/ — ``PipelineModule`` (module.py:86) partitions
layers into stages, ``PipelineEngine`` (engine.py:60) interprets a 1F1B
instruction schedule (schedule.py:189) with explicit P2P send/recv
(p2p.py:46,67).

TPU-native redesign: the schedule is a ``lax.scan`` over
``M + P - 1`` pipeline steps inside a shard_map that is *manual only over
pp* (other mesh axes stay under GSPMD, so fsdp/tp/sp sharding of each
stage's weights keeps working inside). Stage-to-stage transfer is a
``ppermute`` ring shift — the P2P of p2p.py as an ICI/DCN collective.
Autodiff through scan+ppermute yields the backward pipeline (reverse
schedule, reversed ring) with no instruction interpreter; remat on the
stage body keeps per-microbatch liveness at the stage boundary, the role
of the reference's activation-checkpoint interval (pipe/module.py:340).

GPipe-flavored: all M forward steps run before backward begins (autodiff
order), so weight versioning/interleaving issues don't arise; bubble
fraction is (P-1)/(M+P-1) per direction — choose M >= 2P.

1F1B-depth memory: the reference's TrainSchedule (pipe/schedule.py:189)
bounds in-flight microbatches to the stage depth so activation memory
stays O(P) as M grows. Here the M microbatches run in *waves* of
``window`` (default 2P) with the wave body rematerialized: the backward
replays one wave at a time, so live stage-boundary activations are
O(window + P) regardless of M — memory flat as M doubles (asserted via
compiled memory_analysis in tests/test_pipeline.py).

Tied embeddings (reference TiedLayerSpec pipe/module.py:77 + tied-grad
allreduce pipe/engine.py:274): structurally unnecessary here — only the
stacked layer dim shards over pp; embedding/unembed weights stay
replicated over pp under GSPMD, which inserts the gradient psum across
their two uses itself (parity test: tests/test_pipeline.py tied test).
"""

from __future__ import annotations

from contextlib import nullcontext
from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.parallel import topology as topo
from deepspeed_tpu.utils import jaxcompat


def pipeline_enabled(mesh: Optional[Mesh]) -> bool:
    return mesh is not None and mesh.shape.get("pp", 1) > 1


# trace-scoped schedule defaults (config.pipeline.{microbatches,window,
# schedule}): the engine enters this around its own model traces, so two
# engines in one process cannot contaminate each other's pipeline schedule
_CONFIG_MICROBATCHES = 0
_CONFIG_WINDOW = 0
_CONFIG_SCHEDULE = "waves"


class schedule_defaults:
    """``with schedule_defaults(m, w, s): model.loss(...)`` —
    engine-config defaults for pipelined_layers, scoped to the trace."""

    def __init__(self, microbatches: int = 0, window: int = 0,
                 schedule: str = "waves"):
        self._mws = (microbatches, window, schedule)

    def __enter__(self):
        global _CONFIG_MICROBATCHES, _CONFIG_WINDOW, _CONFIG_SCHEDULE
        self._prev = (_CONFIG_MICROBATCHES, _CONFIG_WINDOW, _CONFIG_SCHEDULE)
        _CONFIG_MICROBATCHES, _CONFIG_WINDOW, _CONFIG_SCHEDULE = self._mws

    def __exit__(self, *a):
        global _CONFIG_MICROBATCHES, _CONFIG_WINDOW, _CONFIG_SCHEDULE
        _CONFIG_MICROBATCHES, _CONFIG_WINDOW, _CONFIG_SCHEDULE = self._prev
        return False


def pipelined_layers(layer_fn: Callable, stacked_params: Any, x: jax.Array,
                     num_microbatches: Optional[int] = None,
                     window: Optional[int] = None,
                     with_aux: bool = False,
                     schedule: Optional[str] = None):
    """Run ``scan(layer_fn)`` over [L, ...]-stacked params as a pp-stage
    pipeline.

    layer_fn(carry, layer_params) -> carry, with carry [mb, S, H]; when
    ``with_aux`` it returns (carry, aux_scalar) and the pipeline threads a
    per-microbatch float32 accumulator alongside the activations (MoE
    aux/z losses — the reference accumulates these across the pipe via the
    engine's loss reduction, pipe/engine.py:592).
    x: [B, S, H]; B must divide into num_microbatches (default 2*pp).
    ``window`` caps in-flight microbatches per rematted wave (1F1B-depth
    memory; default 2*pp). Returns [B, S, H] replicated over pp (and,
    when ``with_aux``, the aux *averaged over microbatches* — the same
    mean reduction the reference's pipe engine applies to losses, so the
    aux-loss scale is invariant to the pipeline's microbatch count).

    ``schedule``: "waves" remats each window-sized wave (memory
    O(window+P) for any M, one extra forward per wave); "save_boundaries"
    runs one un-rematted pass whose scan residuals are exactly the
    per-step stage-boundary activations — zero recompute above the
    per-stage remat, memory O(M+P) boundaries (config
    pipeline.schedule).
    """
    mesh = topo.get_global_mesh()
    PP = mesh.shape["pp"]
    B = x.shape[0]
    M = num_microbatches or _CONFIG_MICROBATCHES or min(B, 2 * PP)
    M = min(M, B)
    while B % M != 0:
        M -= 1
    assert M >= 1
    sched = schedule or _CONFIG_SCHEDULE or "waves"
    if sched not in ("waves", "save_boundaries"):
        raise ValueError(f"pipeline schedule must be 'waves' or "
                         f"'save_boundaries', got {sched!r}")
    if sched == "save_boundaries":
        W = M  # single pass; the wave body is not rematted when W == M
    else:
        W = window or _CONFIG_WINDOW or 2 * PP
        W = min(W, M)
        while M % W != 0:
            W -= 1

    L = jax.tree.leaves(stacked_params)[0].shape[0]
    assert L % PP == 0, f"num_layers {L} must divide pp {PP}"

    def per_stage(params_stage, xs_local):
        # params_stage leaves: [L/PP, ...]; xs_local: [M, mb, S, H]
        stage = lax.axis_index("pp")
        fwd_perm = [(i, (i + 1) % PP) for i in range(PP)]

        def stage_fn(inp, params_stage):
            act, aux = inp

            def one_layer(c, p):
                if with_aux:
                    a, l_aux = layer_fn(c[0], p)
                    return (a, c[1] + l_aux), None
                return (layer_fn(c[0], p), c[1]), None

            (act, aux), _ = lax.scan(one_layer, (act, aux), params_stage)
            return act, aux

        stage_fn = jax.checkpoint(stage_fn)

        def wave(xs_wave):
            """One W-microbatch pipeline pass: [W, mb, S, H] →
            (ys [W, mb, S, H] on the last stage, aux scalar)."""
            steps = W + PP - 1

            def body(carry, t):
                buf, aux_buf = carry  # arriving from the previous stage
                mb_idx = jnp.clip(t, 0, W - 1)
                inp = jnp.where(stage == 0, xs_wave[mb_idx], buf)
                aux_in = jnp.where(stage == 0, 0.0, aux_buf)
                out, aux_out = stage_fn((inp, aux_in), params_stage)
                nxt = lax.ppermute(out, "pp", fwd_perm)
                aux_nxt = lax.ppermute(aux_out, "pp", fwd_perm)
                is_valid = jnp.logical_and(stage == PP - 1, t >= PP - 1)
                y = jnp.where(is_valid, out, jnp.zeros_like(out))
                y_aux = jnp.where(is_valid, aux_out, 0.0)
                return (nxt, aux_nxt), (y, y_aux)

            init = (jnp.zeros_like(xs_wave[0]),
                    jnp.asarray(0.0, jnp.float32))
            _, (ys, aux_ys) = lax.scan(body, init, jnp.arange(steps))
            return ys[PP - 1:], aux_ys[PP - 1:].sum()

        if W == M:
            ys, aux_total = wave(xs_local)
        else:
            # waves of W microbatches, wave body rematted: the backward
            # replays one wave at a time, so live boundary activations
            # stay O(W + P) however large M grows (1F1B-depth memory)
            wave_ck = jax.checkpoint(wave)
            xs_waves = xs_local.reshape(M // W, W, *xs_local.shape[1:])
            _, (ys_w, aux_w) = lax.scan(
                lambda c, xw: (c, wave_ck(xw)), 0, xs_waves)
            ys = ys_w.reshape(M, *xs_local.shape[1:])
            aux_total = aux_w.sum()

        # replicate the last stage's result to every stage (out_specs P())
        ys = lax.psum(jnp.where(stage == PP - 1, ys,
                                jnp.zeros_like(ys)), "pp")
        aux_total = lax.psum(jnp.where(stage == PP - 1, aux_total, 0.0), "pp")
        return ys, aux_total

    from deepspeed_tpu.runtime.sharding import force_f32, manual_axes

    # XLA's CPU backend crashes ("Invalid binary instruction opcode copy")
    # on bf16 inside a partial-manual shard_map; upcast the pipeline region
    # to f32 on CPU only (simulation/tests). TPU runs native bf16.
    cast_f32 = (jax.default_backend() == "cpu"
                and any(l.dtype == jnp.bfloat16
                        for l in jax.tree.leaves((stacked_params, x))))
    orig_dtype = x.dtype
    if cast_f32:
        to32 = lambda t: (t.astype(jnp.float32)
                          if t.dtype == jnp.bfloat16 else t)
        stacked_params = jax.tree.map(to32, stacked_params)
        x = to32(x)
    xs = x.reshape(M, B // M, *x.shape[1:])  # [M, mb, S, H]

    param_specs = jax.tree.map(lambda _: P("pp"), stacked_params)
    ctx2 = force_f32() if cast_f32 else nullcontext()
    # the region is manual over pp ONLY: activation constraints and the
    # qwZ int8 fetch stay live inside the stage body with the pp axis
    # stripped from their specs (sharding.manual_axes — same construction
    # as the ZeRO++ dp region, runtime/zeropp.py:116), so fsdp/tp/sp
    # sharding and quantized gathers compose with pipeline stages
    with manual_axes({"pp"}), ctx2:
        out, aux = jaxcompat.shard_map(
            per_stage,
            mesh=mesh,
            in_specs=(param_specs, P()),
            out_specs=(P(), P()),
            axis_names=frozenset({"pp"}),
            check_vma=False,
        )(stacked_params, xs)
    out = out.reshape(B, *x.shape[1:])
    if cast_f32:
        out = out.astype(orig_dtype)
    if with_aux:
        return out, aux / M
    return out
