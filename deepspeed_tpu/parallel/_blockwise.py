"""Shared blockwise-attention numerics for ring (ring_attention.py) and
FPDT chunked attention (fpdt.py).

One implementation of the flash-attention online softmax: a partial
block compute producing unnormalized (o, m, l) statistics, and the
rescale-and-merge of partials into a running accumulator. Both consumers
iterate blocks differently (KV rotating around a ppermute ring vs a
lax.scan over resident KV tiles) but share this math exactly, so a
numerics fix lands in both.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class BlockStats(NamedTuple):
    o: jax.Array      # [B,N,Sq,D] fp32 unnormalized weighted values
    m: jax.Array      # [B,N,Sq] fp32 row max (0 where row fully masked)
    l: jax.Array      # [B,N,Sq] fp32 row sum (0 where row fully masked)
    valid: jax.Array  # [B,N,Sq] bool: any unmasked key in this block


def block_attn_partial(q, k, v, q_pos, k_pos, causal: bool,
                       s_valid: int, seg_q=None, seg_k=None) -> BlockStats:
    """One Q-block × KV-block partial attention in fp32.

    q: [B,Sq,N,D]; k,v: [B,Sk,N,D]; q_pos/k_pos: global positions of the
    rows/keys; keys at positions >= s_valid (padding) are always masked.
    seg_q/seg_k: optional [B,Sq]/[B,Sk] packed-sequence segment ids —
    cross-segment pairs are masked (same contract as the flash kernel's
    segment_ids).
    """
    d = q.shape[-1]
    scores = jnp.einsum("bqnd,bknd->bnqk", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(d, jnp.float32))
    mask = k_pos[None, :] < s_valid
    if causal:
        mask = mask & (k_pos[None, :] <= q_pos[:, None])
    else:
        mask = jnp.broadcast_to(mask, (q_pos.shape[0], k_pos.shape[0]))
    mask = mask[None, None, :, :]
    if seg_q is not None:
        same = seg_q[:, None, :, None] == seg_k[:, None, None, :]  # [B,1,Sq,Sk]
        mask = mask & same
    scores = jnp.where(mask, scores, -jnp.inf)
    m = jnp.max(scores, axis=-1)
    valid = jnp.isfinite(m)
    m_safe = jnp.where(valid, m, 0.0)
    p = jnp.exp(scores - m_safe[..., None])
    l = jnp.where(valid, jnp.sum(p, axis=-1), 0.0)
    o = jnp.einsum("bnqk,bknd->bnqd", p, v.astype(jnp.float32))
    return BlockStats(o, m_safe, l, valid)


def online_merge(o_acc, m_acc, l_acc, blk: BlockStats
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Merge a block's partial stats into the running accumulator
    (o_acc fp32 [B,N,Sq,D]; m_acc fp32 [B,N,Sq] init -inf; l_acc init 0).
    """
    m_new = jnp.maximum(m_acc, jnp.where(blk.valid, blk.m, -jnp.inf))
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.where(jnp.isfinite(m_acc), jnp.exp(m_acc - m_safe), 0.0)
    beta = jnp.where(blk.valid, jnp.exp(blk.m - m_safe), 0.0)
    o_acc = o_acc * alpha[..., None] + blk.o * beta[..., None]
    l_acc = l_acc * alpha + blk.l * beta
    return o_acc, m_new, l_acc


def init_accumulators(B: int, N: int, Sq: int, D: int):
    return (jnp.zeros((B, N, Sq, D), jnp.float32),
            jnp.full((B, N, Sq), -jnp.inf, jnp.float32),
            jnp.zeros((B, N, Sq), jnp.float32))


def finalize(o_acc, l_acc, dtype) -> jax.Array:
    """Normalize and restore [B,Sq,N,D] layout in the caller's dtype."""
    out = o_acc / jnp.maximum(l_acc[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(dtype)
