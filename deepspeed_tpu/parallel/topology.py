"""Device-mesh topology: the TPU-native analog of process groups.

The reference wires parallelism with explicit process groups
(deepspeed/utils/groups.py, runtime/pipe/topology.py:244
``PipeModelDataParallelTopology``). On TPU the same roles become named axes
of one ``jax.sharding.Mesh``; XLA derives the collectives from sharding
annotations, so "creating a group" reduces to "declaring an axis".

Axis roles (product of sizes == device count):

  pp    pipeline stages (collective-permute between stages; usually spans DCN)
  dp    pure data-parallel replicas (ZeRO-0 style; also the hpZ outer axis —
        params replicated here, optimizer state may shard over it)
  fsdp  ZeRO-sharded data parallel (params/grads/opt-state shard here)
  ep    expert parallel (MoE experts shard here; batch also shards here for
        non-MoE parts — reference expert_data_parallel groups
        utils/groups.py:304)
  sp    Ulysses/ring sequence parallel (activations shard on sequence dim)
  tp    tensor parallel (innermost: adjacent devices, fastest ICI hops)

Axis order puts tp innermost so TP collectives ride nearest-neighbour ICI,
and pp outermost so stage boundaries can sit across slices/DCN — the
ICI-vs-DCN analog of the reference's NVLink-vs-IB distinction (SURVEY §5).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.utils.logging import logger

# canonical axis order, outermost → innermost
MESH_AXES = ("pp", "dp", "fsdp", "ep", "sp", "tp")

# logical→mesh axis names for activations
BATCH_AXES = ("dp", "fsdp", "ep")  # batch dim shards over all data axes
SEQ_AXIS = "sp"


@dataclasses.dataclass(frozen=True)
class TopologyConfig:
    """Requested per-axis degrees. ``-1`` on at most one axis = absorb the
    remaining devices (like the reference letting dp = world/(tp*pp*ep),
    utils/groups.py)."""

    pp: int = 1
    dp: int = -1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def sizes(self, n_devices: int) -> Dict[str, int]:
        req = {a: getattr(self, a) for a in MESH_AXES}
        for a, v in req.items():
            if v != -1 and v < 1:
                raise ValueError(f"mesh axis '{a}' size must be >= 1 or -1, got {v}")
        free = [a for a, v in req.items() if v == -1]
        if len(free) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {free}")
        fixed = math.prod(v for v in req.values() if v != -1)
        if free:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"device count {n_devices} not divisible by fixed axes product {fixed}"
                )
            req[free[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh axes product {fixed} != device count {n_devices}"
            )
        return req


def build_mesh(
    topo: TopologyConfig | Dict[str, int] | None = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the framework's single device mesh.

    Devices are laid out so the innermost axes (tp, sp) map to adjacent
    devices. On real TPU slices ``jax.devices()`` order already follows the
    torus; ``mesh_utils.create_device_mesh`` improves ICI contiguity when
    available.
    """
    if devices is None:
        devices = jax.devices()
    if topo is None:
        topo = TopologyConfig()
    elif isinstance(topo, dict):
        unknown = set(topo) - set(MESH_AXES)
        if unknown:
            raise ValueError(
                f"unknown mesh axes {sorted(unknown)}; valid axes: {MESH_AXES}"
            )
        topo = TopologyConfig(**topo)
    sizes = topo.sizes(len(devices))
    shape = tuple(sizes[a] for a in MESH_AXES)
    try:
        from jax.experimental import mesh_utils

        device_array = mesh_utils.create_device_mesh(
            shape, devices=list(devices), allow_split_physical_axes=True
        )
    except Exception as e:  # CPU-sim or odd shapes: fall back to row-major
        logger.debug(f"mesh_utils.create_device_mesh failed ({e}); using reshape")
        device_array = np.asarray(list(devices)).reshape(shape)
    mesh = Mesh(device_array, MESH_AXES)
    logger.info(
        "mesh: "
        + " × ".join(f"{a}={sizes[a]}" for a in MESH_AXES if sizes[a] > 1 or a == "dp")
    )
    return mesh


# ---------------------------------------------------------------------------
# group-size queries (reference: deepspeed/utils/groups.py getters)
# ---------------------------------------------------------------------------

_GLOBAL_MESH: Optional[Mesh] = None


def set_global_mesh(mesh: Mesh) -> None:
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_global_mesh() -> Mesh:
    if _GLOBAL_MESH is None:
        raise RuntimeError(
            "no global mesh set; call deepspeed_tpu.initialize() or "
            "topology.set_global_mesh(mesh) first"
        )
    return _GLOBAL_MESH


class use_mesh:
    """Scope the global mesh to one engine's mesh for the duration of a
    step/trace. Two engines in one process each set the global mesh at
    init; whichever initialized LAST would otherwise win inside the
    other's traces (constraints, vocab-parallel lookups), compiling
    against the wrong device assignment."""

    def __init__(self, mesh: Mesh):
        self._mesh = mesh

    def __enter__(self):
        global _GLOBAL_MESH
        self._prev = _GLOBAL_MESH
        _GLOBAL_MESH = self._mesh

    def __exit__(self, *a):
        global _GLOBAL_MESH
        _GLOBAL_MESH = self._prev
        return False


def _axis_size(mesh: Optional[Mesh], axis: str) -> int:
    mesh = mesh or get_global_mesh()
    return mesh.shape[axis]


def get_data_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    """Total data parallelism = dp × fsdp × ep (reference
    groups._get_data_parallel_world_size)."""
    mesh = mesh or get_global_mesh()
    return math.prod(mesh.shape[a] for a in BATCH_AXES)


def get_model_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, "tp")


def get_tensor_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, "tp")


def get_pipeline_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, "pp")


def get_expert_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, "ep")


def get_sequence_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, "sp")


def get_fsdp_world_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, "fsdp")


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a [batch, ...] host array: batch over all data axes,
    sequence dim (dim 1) over sp if present."""
    return NamedSharding(mesh, PartitionSpec(BATCH_AXES, SEQ_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
