"""Ring attention: blockwise context parallelism over the sp mesh axis.

The reference has NO ring attention (SURVEY.md §5: long context is
Ulysses/ALST/FPDT only) — but Ulysses caps the sequence-parallel degree at
the head count (sequence/layer.py head-scatter). Ring attention removes
that cap: KV blocks rotate around the sp axis via ``ppermute`` on ICI
while each chip keeps its resident Q block, accumulating the exact
softmax online (flash-attention style), so sp can exceed num_heads and
sequence length scales with the ring size. This is the TPU-native
long-context path that complements parallel/ulysses.py:

  * Ulysses: 2 all-to-alls, full-sequence local attention — best when
    sp <= heads and the sequence fits one chip's HBM.
  * Ring: p-1 ppermute hops overlapped with per-block attention compute —
    best when sp > heads or S/p is all that fits.

Causality is handled by global position masking, so the math matches
dense causal attention bit-for-bit in fp32 accumulation. Gradients flow
through ``lax.scan`` + ``ppermute`` (transpose of a permute is the
inverse permute), giving the exact backward without a hand-written
kernel.

The sp axis must already shard the sequence dim of q/k/v (the engine's
sharding plan does this when sequence_parallel.size > 1).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import comm
from deepspeed_tpu.parallel import topology
from deepspeed_tpu.utils import jaxcompat

BATCH = ("dp", "fsdp", "ep")


def _ring_attn_local(q, k, v, seg, *, axis: str, causal: bool,
                     s_global: int):
    """Runs INSIDE shard_map: q,k,v are the local [B, S/p, N_loc, D]
    blocks; rotates kv (and its segment-id block, for packed batches)
    around ``axis`` accumulating exact softmax (shared numerics in
    parallel/_blockwise.py). ``seg`` is the local [B, S/p] segment-id
    block or a [B, 0] placeholder when the batch is unpacked."""
    from deepspeed_tpu.parallel._blockwise import (
        block_attn_partial, finalize, init_accumulators, online_merge)

    p_size = jaxcompat.axis_size(axis)
    my_idx = lax.axis_index(axis)
    s_loc = q.shape[1]
    q_pos = my_idx * s_loc + jnp.arange(s_loc)
    has_seg = seg.shape[1] > 0

    dt = q.dtype
    B, _, N, D = q.shape
    o_acc, m_acc, l_acc = init_accumulators(B, N, s_loc, D)

    # remat the per-step block: the ring scan's backward would otherwise
    # stack every step's [S/p, S/p] softmax block as a residual —
    # [p, B, N, S/p, S/p] fp32, the O(S^2/p) memory blowup this path
    # exists to avoid (same leak class as fpdt's inner tile scan)
    ck_block = jax.checkpoint(
        lambda q_, k_, v_, qp, kp, sq, sk: block_attn_partial(
            q_, k_, v_, qp, kp, causal, s_global, seg_q=sq, seg_k=sk))

    def body(carry, step):
        k_blk, v_blk, seg_blk, o_acc, m_acc, l_acc = carry
        kv_idx = (my_idx - step) % p_size
        k_pos = kv_idx * s_loc + jnp.arange(s_loc)
        blk = ck_block(q, k_blk, v_blk, q_pos, k_pos,
                       seg if has_seg else None,
                       seg_blk if has_seg else None)
        o_acc, m_acc, l_acc = online_merge(o_acc, m_acc, l_acc, blk)
        # rotate kv forward around the ring (device i -> i+1) — via the
        # traced comm facade so each hop gets a flight-recorder span and
        # a chrome-trace collective-lane slice (bytes are per-hop local
        # block size; the scan dispatches the hop once at trace time)
        perm = [(i, (i + 1) % p_size) for i in range(p_size)]
        k_blk = comm.ppermute(k_blk, axis, perm,
                              log_name="ring_attention_kv")
        v_blk = comm.ppermute(v_blk, axis, perm,
                              log_name="ring_attention_kv")
        if has_seg:
            seg_blk = comm.ppermute(seg_blk, axis, perm,
                                    log_name="ring_attention_seg")
        return (k_blk, v_blk, seg_blk, o_acc, m_acc, l_acc), None

    (k, v, seg, o_acc, m_acc, l_acc), _ = lax.scan(
        body, (k, v, seg, o_acc, m_acc, l_acc), jnp.arange(p_size))

    return finalize(o_acc, l_acc, dt)  # [B,S/p,N,D]


def ring_attention(q, k, v, causal: bool = True, axis: str = "sp",
                   segment_ids: Optional[jax.Array] = None):
    """Context-parallel attention; drop-in for multi_head_attention when
    the sequence dim is sharded over ``axis``.

    q,k,v: [B, S, N, D] global (kv heads already repeated for GQA, same
    contract as ops/attention.py multi_head_attention). segment_ids
    [B, S] mask cross-segment attention for packed batches — the id
    block rotates around the ring with its KV block.
    """
    from deepspeed_tpu.ops.attention import multi_head_attention

    mesh = topology._GLOBAL_MESH
    if mesh is None or axis not in mesh.shape or mesh.shape[axis] == 1:
        from deepspeed_tpu.utils import telemetry

        telemetry.count(
            "ring_attention.dense_fallback",
            f"no mesh axis '{axis}' > 1 — running dense attention")
        return multi_head_attention(q, k, v, causal=causal,
                                    segment_ids=segment_ids)

    p_size = mesh.shape[axis]

    # pad S to a multiple of the ring size; padded KV positions are masked
    # inside the blockwise compute, padded Q rows are sliced off
    S = q.shape[1]
    pad = (-S) % p_size
    if pad:
        widths = [(0, 0), (0, pad), (0, 0), (0, 0)]
        q, k, v = (jnp.pad(t, widths) for t in (q, k, v))
    if segment_ids is None:
        # zero-width placeholder: shard_map wants a concrete operand, the
        # local body skips segment masking when it sees width 0
        seg = jnp.zeros((q.shape[0], 0), jnp.int32)
    else:
        # padded keys are masked by position already; -1 also keeps them
        # out of any real segment
        seg = jnp.pad(segment_ids.astype(jnp.int32), [(0, 0), (0, pad)],
                      constant_values=-1)

    batch_axes = tuple(a for a in BATCH if a in mesh.shape)
    spec = P(batch_axes, axis, "tp" if "tp" in mesh.shape else None, None)
    seg_spec = P(batch_axes, None if seg.shape[1] == 0 else axis)
    fn = jaxcompat.shard_map(
        partial(_ring_attn_local, axis=axis, causal=causal, s_global=S),
        mesh=mesh, in_specs=(spec, spec, spec, seg_spec), out_specs=spec,
        check_vma=False)
    out = fn(q, k, v, seg)
    return out[:, :S] if pad else out
