"""ALST/arctic-style tiled compute: trade FLOPs scheduling for activation
memory on long sequences.

Reference: runtime/sequence_parallel/ulysses_sp.py —
``sequence_tiled_compute`` (:720) applies a module over sequence shards;
``TiledMLP`` (:564) chunks the MLP over the sequence dim; and
``TiledFusedLogitsLoss`` (:943) computes the unembed-projection + loss
per tile so the [B, S, V] logits tensor never materializes (the dominant
activation at long S and 100k+ vocab).

TPU-native form: a ``lax.scan`` over sequence tiles with
``jax.checkpoint`` on the tile body — the scan carries only the running
reduction, remat recomputes tile activations in backward, and XLA
pipelines the tiles. Zero Python-level loops; fully jit-traceable.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _split_tiles(x: jax.Array, n_tiles: int, axis: int = 1):
    """[..., S, ...] -> (n_tiles, tile) leading structure for scan; pads S
    up to a multiple of n_tiles. Returns (tiles, orig_len)."""
    S = x.shape[axis]
    pad = (-S) % n_tiles
    if pad:
        widths = [(0, 0)] * x.ndim
        widths[axis] = (0, pad)
        x = jnp.pad(x, widths)
    tile = (S + pad) // n_tiles
    new_shape = (x.shape[:axis] + (n_tiles, tile) + x.shape[axis + 1:])
    x = x.reshape(new_shape)
    # move the n_tiles dim to the front for scan
    x = jnp.moveaxis(x, axis, 0)
    return x, S


def sequence_tiled_compute(fn: Callable, x: jax.Array, n_tiles: int,
                           axis: int = 1, checkpoint: bool = True):
    """Apply ``fn`` (shape-preserving along ``axis``) tile-by-tile.

    fn: tile -> tile, where tile has the same rank as x with the sequence
    dim shortened. Backward recomputes each tile (remat) so peak
    activation memory is one tile's worth.
    """
    if n_tiles <= 1:
        return fn(x)
    tiles, S = _split_tiles(x, n_tiles, axis)
    body_fn = jax.checkpoint(fn) if checkpoint else fn

    def body(_, tile):
        return None, body_fn(tile)

    _, out = lax.scan(body, None, tiles)
    out = jnp.moveaxis(out, 0, axis)
    out = out.reshape(out.shape[:axis] + (-1,) + out.shape[axis + 2:])
    return lax.slice_in_dim(out, 0, S, axis=axis)


def tiled_mlp(mlp_fn: Callable, x: jax.Array, n_tiles: int,
              checkpoint: bool = True):
    """MLPs are position-wise — chunk the sequence dim (reference TiledMLP
    ulysses_sp.py:564)."""
    return sequence_tiled_compute(mlp_fn, x, n_tiles, axis=1,
                                  checkpoint=checkpoint)


def tiled_logits_loss(hidden: jax.Array, unembed: jax.Array,
                      labels: jax.Array, mask: Optional[jax.Array],
                      n_tiles: int, transpose_unembed: bool = False,
                      tile_transform=None) -> Tuple[jax.Array, jax.Array]:
    """Fused unembed + causal-LM cross-entropy without materializing
    [B, S, V] logits (reference TiledFusedLogitsLoss ulysses_sp.py:943).

    hidden: [B, S, H]; unembed: [V, H] (tied embedding) or [H, V] with
    ``transpose_unembed=False``; labels: [B, S] int; mask: [B, S] or None.
    ``tile_transform`` (e.g. the model's final norm) applies to each
    hidden tile inside the rematted tile body, so its fp32 intermediates
    stay tile-sized (reference chunks final-norm+logits the same way,
    fpdt_layer.py:1207). Returns (masked_nll_sum, mask_total) — caller
    divides.
    """
    B, S, H = hidden.shape
    if mask is None:
        mask = jnp.ones((B, S), jnp.float32)
    mask = mask.astype(jnp.float32)
    if n_tiles <= 1:
        n_tiles = 1
    h_tiles, _ = _split_tiles(hidden, n_tiles, axis=1)
    l_tiles, _ = _split_tiles(labels, n_tiles, axis=1)
    m_tiles, _ = _split_tiles(mask, n_tiles, axis=1)

    def tile_nll(h, lbl, m):
        if tile_transform is not None:
            h = tile_transform(h)
        if transpose_unembed:
            logits = jnp.einsum("bsh,vh->bsv", h, unembed)
        else:
            logits = jnp.einsum("bsh,hv->bsv", h, unembed)
        logits = logits.astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, lbl[..., None], axis=-1)[..., 0]
        nll = (logz - gold) * m
        return jnp.sum(nll), jnp.sum(m)

    tile_nll = jax.checkpoint(tile_nll)

    def body(carry, xs):
        acc_nll, acc_m = carry
        h, lbl, m = xs
        s_nll, s_m = tile_nll(h, lbl, m)
        return (acc_nll + s_nll, acc_m + s_m), None

    (total_nll, total_m), _ = lax.scan(
        body, (jnp.asarray(0.0, jnp.float32), jnp.asarray(0.0, jnp.float32)),
        (h_tiles, l_tiles, m_tiles))
    return total_nll, total_m
