"""Ulysses sequence parallelism, the GSPMD way.

The reference implements Ulysses (deepspeed/sequence/layer.py:351
``DistributedAttention``) with two explicit all-to-alls: qkv arrive
sequence-sharded [s/p, h]; an all-to-all regroups to head-sharded [s, h/p];
local attention runs over the full sequence; a second all-to-all restores
sequence sharding (``_SeqAllToAll`` sequence/layer.py:297,
``single_all_to_all`` :241).

On TPU the same dataflow is expressed as two sharding constraints: change
the activation's PartitionSpec from seq-sharded to head-sharded and GSPMD
emits the all-to-all on the sp axis of the ICI mesh — including the
comm/compute overlap the reference builds by hand with side streams
(sequence/layer.py fwd :387), courtesy of XLA's latency-hiding scheduler.

Uneven head counts (reference uneven_heads_all2all sequence/layer.py:131)
need no special casing: GSPMD handles non-divisible shardings by padding.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm import comm
from deepspeed_tpu.parallel import topology

BATCH = ("dp", "fsdp", "ep")


def _constrain(x, spec: P):
    mesh = topology._GLOBAL_MESH
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def ulysses_attention(q, k, v, causal: bool = True, impl: str = "auto",
                      segment_ids: Optional[jax.Array] = None,
                      attn_chunks: int = 0):
    """Attention over a sequence-sharded input.

    q,k,v: [B, S, N, D] logically; physically S is sharded over sp on entry
    and exit. Inside, heads are sharded over (tp, sp) and S is full — the
    head-scatter layout of the reference's DistributedAttention.forward.
    ``attn_chunks > 1`` runs the full-sequence local attention through the
    FPDT-style chunked path (parallel/fpdt.py) to bound score memory.
    """
    from deepspeed_tpu.ops.attention import multi_head_attention

    def local_attn(q, k, v):
        if attn_chunks > 1:
            from deepspeed_tpu.parallel.fpdt import chunked_attention

            return chunked_attention(q, k, v, causal=causal,
                                     q_chunks=attn_chunks)
        return multi_head_attention(q, k, v, causal=causal, impl=impl,
                                    segment_ids=segment_ids)

    mesh = topology._GLOBAL_MESH
    if mesh is None or mesh.shape["sp"] == 1:
        return local_attn(q, k, v)

    # seq-sharded -> head-sharded (all-to-all #1, on ICI). The
    # collectives are GSPMD-implicit (emitted from the sharding
    # constraints), so wrap each constraint in comm.traced_span to give
    # them the facade's byte accounting + flight-recorder spans
    inner = P(BATCH, None, ("tp", "sp"), None)
    with comm.traced_span("all_to_all", q, "sp", "ulysses_qkv"):
        q = _constrain(q, inner)
    with comm.traced_span("all_to_all", k, "sp", "ulysses_qkv"):
        k = _constrain(k, inner)
    with comm.traced_span("all_to_all", v, "sp", "ulysses_qkv"):
        v = _constrain(v, inner)

    out = local_attn(q, k, v)

    # head-sharded -> seq-sharded (all-to-all #2)
    with comm.traced_span("all_to_all", out, "sp", "ulysses_out"):
        return _constrain(out, P(BATCH, "sp", "tp", None))


# ---------------------------------------------------------------------------
# sequence-sharded data feeding (reference UlyssesSPDataLoaderAdapter,
# runtime/sequence_parallel/ulysses_sp.py:564 — each sp rank feeds its
# sequence chunk so multi-M-token batches never materialize whole on one
# host)
# ---------------------------------------------------------------------------


class UlyssesSPDataLoaderAdapter:
    """Wrap a host batch iterator so token tensors land sequence-sharded
    over the ``sp`` mesh axis (batch dim over the data axes, dim 1 over
    sp). Single-process: one device_put with the seq-sharded layout.
    Multi-host: each process contributes only its local shard via
    ``make_array_from_process_local_data`` — the ALST contract where no
    host ever holds the full sequence.
    """

    def __init__(self, loader, mesh, sp_axis: str = "sp",
                 seq_dim: int = 1):
        from deepspeed_tpu.parallel.topology import BATCH_AXES

        self.loader = loader
        self.mesh = mesh
        self.sp_axis = sp_axis
        self.seq_dim = seq_dim
        # ADVICE r1: .get(a, 1) >= 1 was vacuously true; filter to axes
        # the mesh actually has so user-supplied meshes without dp/fsdp/
        # ep don't fail at shard time
        batch_axes = tuple(a for a in BATCH_AXES if a in mesh.shape)
        self._batch_axes = batch_axes

    def shard(self, batch):
        import jax
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        def put(x):
            x = np.asarray(x)
            # rank-aware spec (the old version padded 8 trailing dims):
            # batch axes on dim 0, sp on the sequence dim, rest unsharded
            if x.ndim <= self.seq_dim:
                sh = NamedSharding(self.mesh, P(self._batch_axes))
            else:
                spec = [None] * x.ndim
                spec[0] = self._batch_axes
                spec[self.seq_dim] = self.sp_axis
                sh = NamedSharding(self.mesh, P(*spec))
            if jax.process_count() > 1:
                return jax.make_array_from_process_local_data(sh, x)
            return jax.device_put(x, sh)

        return jax.tree.map(put, batch)

    def __iter__(self):
        for batch in self.loader:
            yield self.shard(batch)
