"""AutoSP: automatic sequence-parallel strategy selection.

Reference: ``deepspeed/sequence/auto_sp.py:42``
(``auto_wrap_model_for_sp``) + ``autosp_detector.py`` + the DeepCompile
pass ``compile/passes/sp_compile.py`` — detect attention in the model's
graph and rewrite it to Ulysses sequence parallelism automatically.

TPU-native: there is no graph surgery to do — our models express
attention through one dispatcher, so "rewriting to Ulysses" is flipping
``sequence_parallel`` in the model config. What remains genuinely
automatic is the *strategy choice*, which the reference leaves to the
user: Ulysses's head-scatter all-to-all requires attention heads ≥ sp
degree (each rank needs ≥ 1 head); when heads (or KV heads, which bound
the scatter for GQA) are fewer than sp, ring attention (ppermute context
parallelism) is the right mechanism. ``auto_wrap_model_for_sp`` inspects
the mesh and the model's head layout and picks.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from deepspeed_tpu.utils.logging import log_dist, logger


def detect_sp_strategy(num_heads: int, num_kv_heads: Optional[int],
                       sp_size: int) -> Optional[str]:
    """'ulysses' | 'ring' | None (sp off). The head-scatter all-to-all
    needs heads divisible by (or at least ≥) sp; GQA KV heads bound it
    (reference uneven_heads_all2all handles remainders — here the ring
    path covers that regime outright)."""
    if sp_size <= 1:
        return None
    kv = num_kv_heads or num_heads
    if num_heads % sp_size == 0 and kv % sp_size == 0:
        return "ulysses"
    # heads indivisible by (or fewer than) sp: ulysses would pad or
    # starve ranks of heads — ring shards the sequence dim instead
    return "ring"


def auto_wrap_model_for_sp(model, mesh=None, force: Optional[str] = None):
    """Enable sequence parallelism on a zoo model when the mesh has an sp
    axis (reference auto_wrap_model_for_sp sequence/auto_sp.py:42).

    Returns the model (a new instance when the config changed). ``force``
    overrides the detected strategy ('ulysses'/'ring').
    """
    from deepspeed_tpu.parallel import topology

    mesh = mesh or topology._GLOBAL_MESH
    sp = int(mesh.shape.get("sp", 1)) if mesh is not None else 1
    cfg = getattr(model, "config", None)
    if cfg is None or not hasattr(cfg, "num_heads"):
        logger.warning("auto_sp: model has no head config; left unchanged")
        return model
    strategy = force or detect_sp_strategy(
        cfg.num_heads, getattr(cfg, "num_kv_heads", None), sp)
    if strategy is None:
        if getattr(cfg, "sequence_parallel", False):
            cfg = dataclasses.replace(cfg, sequence_parallel=False)
            return type(model)(cfg)
        return model
    new_cfg = dataclasses.replace(cfg, sequence_parallel=True,
                                  sp_mode=strategy)
    log_dist(f"auto_sp: sp={sp} heads={cfg.num_heads}/"
             f"{getattr(cfg, 'num_kv_heads', None) or cfg.num_heads} → "
             f"{strategy}", ranks=[0])
    return type(model)(new_cfg)
