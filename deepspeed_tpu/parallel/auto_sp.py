"""AutoSP: unified sequence-parallel planning (Ulysses × ring × FPDT).

Reference: ``deepspeed/sequence/auto_sp.py:42``
(``auto_wrap_model_for_sp``) + ``autosp_detector.py`` + the DeepCompile
pass ``compile/passes/sp_compile.py`` — detect attention in the model's
graph and rewrite it to Ulysses sequence parallelism automatically.

TPU-native: there is no graph surgery to do — our models express
attention through one dispatcher, so "rewriting to Ulysses" is flipping
``sequence_parallel`` in the model config. Two levels of automation
live here:

  * ``detect_sp_strategy`` — the strategy choice the reference leaves
    to the user: Ulysses's head-scatter all-to-all requires attention
    heads ≥ sp degree (each rank needs ≥ 1 head; KV heads bound the
    scatter for GQA); otherwise ring attention (ppermute context
    parallelism) shards the sequence dim instead.
  * ``plan_sequence_parallel`` — the full long-context composition
    (ROADMAP item 4): given (seq_len, heads, kv_heads, mesh,
    hbm_budget) it returns an :class:`SPPlan` choosing the sp strategy
    and degree, the FPDT q-chunk count, whether the KV stacks spill to
    host (``fpdt_host_kv`` — composes with sp via the shard_map path in
    models/transformer.py since the planner PR), and an
    ``overlap_depth`` interplay hint (PR 6's per-layer overlap engine
    hides the host KV stream behind chunk compute the same way it hides
    the param stream). The engine applies the plan to the model config
    at init when the mesh has an sp axis (runtime/engine.py).

All decisions are deterministic pure functions of their inputs so the
planner grid is unit-testable without a TPU (tests/test_auto_sp.py).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from deepspeed_tpu.utils.logging import log_dist, logger


def detect_sp_strategy(num_heads: int, num_kv_heads: Optional[int],
                       sp_size: int) -> Optional[str]:
    """'ulysses' | 'ring' | None (sp off). The head-scatter all-to-all
    needs heads divisible by (or at least ≥) sp; GQA KV heads bound it
    (reference uneven_heads_all2all handles remainders — here the ring
    path covers that regime outright)."""
    if sp_size <= 1:
        return None
    kv = num_kv_heads or num_heads
    if num_heads % sp_size == 0 and kv % sp_size == 0:
        return "ulysses"
    # heads indivisible by (or fewer than) sp: ulysses would pad or
    # starve ranks of heads — ring shards the sequence dim instead
    return "ring"


def auto_wrap_model_for_sp(model, mesh=None, force: Optional[str] = None):
    """Enable sequence parallelism on a zoo model when the mesh has an sp
    axis (reference auto_wrap_model_for_sp sequence/auto_sp.py:42).

    Returns the model (a new instance when the config changed). ``force``
    overrides the detected strategy ('ulysses'/'ring').
    """
    from deepspeed_tpu.parallel import topology

    mesh = mesh or topology._GLOBAL_MESH
    sp = int(mesh.shape.get("sp", 1)) if mesh is not None else 1
    cfg = getattr(model, "config", None)
    if cfg is None or not hasattr(cfg, "num_heads"):
        logger.warning("auto_sp: model has no head config; left unchanged")
        return model
    strategy = force or detect_sp_strategy(
        cfg.num_heads, getattr(cfg, "num_kv_heads", None), sp)
    if strategy is None:
        if getattr(cfg, "sequence_parallel", False):
            cfg = dataclasses.replace(cfg, sequence_parallel=False)
            return type(model)(cfg)
        return model
    new_cfg = dataclasses.replace(cfg, sequence_parallel=True,
                                  sp_mode=strategy)
    log_dist(f"auto_sp: sp={sp} heads={cfg.num_heads}/"
             f"{getattr(cfg, 'num_kv_heads', None) or cfg.num_heads} → "
             f"{strategy}", ranks=[0])
    return type(model)(new_cfg)


# ---------------------------------------------------------------------------
# unified long-context planner (ROADMAP item 4)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SPPlan:
    """A composed sequence-parallel plan.

    ``strategy`` is the sp attention mechanism ('ulysses' | 'ring' |
    None when sp is off); ``attn_chunks`` the FPDT q-chunk count (0 =
    unchunked); ``fpdt_host_kv`` whether the KV tile stacks spill to
    pinned host memory (utils/memspace.py — identity placement on
    single-memory backends); ``overlap_depth_hint`` how many chunk
    stages of host-KV streaming PR 6's overlap engine should pin behind
    compute (0 = no hint). ``reasons`` carries the human-readable
    decision trail for logs and the bench JSON line.
    """

    strategy: Optional[str]
    sp_degree: int
    attn_chunks: int
    fpdt_host_kv: bool
    fpdt_host_residual: bool = False
    overlap_depth_hint: int = 0
    reasons: Tuple[str, ...] = ()

    def apply(self, cfg):
        """Compose the plan onto a TransformerConfig, conservatively:
        only fields still at their defaults change, so an explicit user
        choice (sp_mode, attn_chunks, fpdt_host_kv, overlap_depth) is
        never overridden. Returns a new config (or ``cfg`` unchanged)."""
        updates = {}
        if self.strategy is not None \
                and not getattr(cfg, "sequence_parallel", False):
            updates["sequence_parallel"] = True
            updates["sp_mode"] = self.strategy
        if self.attn_chunks > 1 \
                and getattr(cfg, "attn_chunks", 0) in (0, 1):
            updates["attn_chunks"] = self.attn_chunks
        if self.fpdt_host_kv and not getattr(cfg, "fpdt_host_kv", False):
            updates["fpdt_host_kv"] = True
        if self.overlap_depth_hint \
                and not getattr(cfg, "overlap_depth", 0) \
                and hasattr(cfg, "overlap_depth"):
            updates["overlap_depth"] = self.overlap_depth_hint
        if not updates:
            return cfg
        return dataclasses.replace(cfg, **updates)


def _sp_degree_of(mesh) -> int:
    """sp degree from a Mesh, a bare int (bench/CLI convenience — plan
    for a simulated degree without building a device mesh), or None."""
    if mesh is None:
        return 1
    if isinstance(mesh, int):
        return max(1, int(mesh))
    shape = getattr(mesh, "shape", None)
    if shape is None:
        return 1
    return int(dict(shape).get("sp", 1))


def _pick_chunks(s_loc: int, target_tokens: int) -> int:
    """Smallest power-of-2 chunk count dividing ``s_loc`` whose chunk
    length is ≤ ``target_tokens`` — power-of-2 so the grid keeps
    dividing under further sp resharding, and a divisor of s_loc so the
    sp composition stays pad-free."""
    c = 1
    while s_loc // c > target_tokens and s_loc % (c * 2) == 0:
        c *= 2
    return c


# With no HBM budget given, chunk so one q-chunk stays at most this many
# tokens — the regime where the [C × kv_tile] fp32 score block (not the
# residual) stops dominating peak memory.
_DEFAULT_CHUNK_TOKENS = 4096


def plan_sequence_parallel(seq_len: int, num_heads: int,
                           num_kv_heads: Optional[int], mesh=None,
                           hbm_budget: Optional[int] = None, *,
                           head_dim: int = 128,
                           hidden_size: Optional[int] = None,
                           batch_size: int = 1,
                           dtype_bytes: int = 2) -> SPPlan:
    """Compose a long-context plan for one step shape.

    ``mesh`` may be a device Mesh (sp degree read from its 'sp' axis),
    a bare int degree, or None. ``hbm_budget`` is per-chip bytes
    available for activations; None plans without spill pressure (the
    deterministic no-budget plan). Pure function — no device access.

    Decision order: (1) sp degree and strategy from the mesh and head
    layout (`detect_sp_strategy`); (2) FPDT chunk count so one chunk's
    fp32 score block fits the budget slice (power-of-2 divisor of the
    LOCAL shard — the sp composition is pad-free); (3) host-KV spill
    when the full-sequence KV stacks at kv_heads width would eat more
    than a quarter of the budget; (4) overlap_depth hint = chunk stages
    the PR 6 engine can pin the host KV stream behind.
    """
    sp = _sp_degree_of(mesh)
    kv = num_kv_heads or num_heads
    hidden = hidden_size or num_heads * head_dim
    strategy = detect_sp_strategy(num_heads, num_kv_heads, sp)
    s_loc = -(-seq_len // sp)
    reasons = []
    if strategy is None:
        reasons.append(f"sp={sp}: sequence parallelism off")
    else:
        reasons.append(
            f"sp={sp} heads={num_heads}/{kv} → {strategy} "
            + ("(head-scatter divides)" if strategy == "ulysses"
               else "(heads indivisible by sp → ring)"))

    # (2) chunk grid — local shard, pad-free divisors only
    if hbm_budget is not None:
        # one chunk's score block is B·N·C² fp32; budget a sixteenth
        target = max(int((hbm_budget
                          / (16.0 * 4.0 * batch_size * num_heads)) ** 0.5),
                     256)
    else:
        target = _DEFAULT_CHUNK_TOKENS
    chunks = _pick_chunks(s_loc, target)

    # (3) host-KV spill: the composed path's device transient is the
    # sp-gathered full-S KV at kv_heads width; spill when it crowds HBM
    kv_bytes = 2 * batch_size * seq_len * kv * head_dim * dtype_bytes
    spill = hbm_budget is not None and kv_bytes > hbm_budget // 4
    if spill:
        reasons.append(
            f"KV stacks {kv_bytes / 2**30:.2f} GiB > budget/4 "
            f"(budget {hbm_budget / 2**30:.2f} GiB) → fpdt_host_kv")
        if chunks < 2:
            if sp <= 1 or s_loc % 2 == 0:
                chunks = 2  # the fpdt path needs ≥ 2 q chunks
            else:
                spill = False
                reasons.append(
                    f"local shard {s_loc} has no even chunk grid — "
                    "cannot stream host KV pad-free under sp; spill off")
    elif hbm_budget is not None:
        reasons.append(
            f"KV stacks {kv_bytes / 2**30:.2f} GiB fit on device "
            "(no spill)")
    if chunks > 1:
        reasons.append(
            f"attn_chunks={chunks} (local shard {s_loc} → "
            f"{s_loc // chunks}-token chunks ≤ target {target})")

    if spill:
        from deepspeed_tpu.utils import memspace

        if not memspace.memories_supported():
            reasons.append(
                "host spill degrades to device placement on this "
                "single-memory backend (CPU sim) — placement semantics "
                "and numerics preserved")

    # (4) overlap interplay: each q chunk's KV refetch is a pinnable
    # stage for the PR 6 overlap engine, like the param-stream ring
    overlap_hint = min(4, chunks) if spill and chunks > 1 else 0
    if overlap_hint:
        reasons.append(
            f"overlap_depth={overlap_hint}: pin host-KV chunk streams "
            "behind per-chunk attention compute")

    residual_bytes = batch_size * s_loc * hidden * dtype_bytes
    if hbm_budget is not None and residual_bytes > hbm_budget // 4:
        reasons.append(
            f"NOTE: per-layer residual {residual_bytes / 2**30:.2f} GiB "
            "also crowds the budget — consider fpdt_host_residual "
            "(single-chip only; does not compose with sp)")

    return SPPlan(strategy=strategy, sp_degree=sp,
                  attn_chunks=chunks if chunks > 1 else 0,
                  fpdt_host_kv=spill,
                  overlap_depth_hint=overlap_hint,
                  reasons=tuple(reasons))
