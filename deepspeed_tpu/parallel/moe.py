"""Mixture-of-Experts: top-k gating + expert-parallel dispatch.

Reference: deepspeed/moe/sharded_moe.py — ``top1gating`` :184,
``top2gating`` :291, ``topkgating`` :375, ``MOELayer.forward`` :589-685
(einsum dispatch, two all-to-alls around local experts), aux
load-balancing losses; expert groups deepspeed/utils/groups.py:304.

TPU-native shape: the dispatch/combine tensors are einsums (exactly the
GShard formulation the reference follows), and the "two all-to-alls" are
not explicit calls — expert weights shard over the ``ep`` mesh axis and
the dispatched activations get a sharding constraint onto ``ep``, so
GSPMD emits the token all-to-all pair on ICI. Capacity-style static
shapes keep everything jit-compatible (no ragged dispatch in the train
path; ragged decode lives in the inference stack).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.runtime.sharding import constrain_activation
from deepspeed_tpu.utils import jaxcompat


@dataclasses.dataclass(frozen=True)
class GateConfig:
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    drop_tokens: bool = True
    aux_loss_weight: float = 0.01
    z_loss_weight: float = 0.0


def compute_capacity(tokens_per_group: int, cfg: GateConfig,
                     train: bool = True) -> int:
    """Reference _capacity (sharded_moe.py:91)."""
    factor = cfg.capacity_factor if train else cfg.eval_capacity_factor
    cap = int(tokens_per_group * factor * cfg.top_k / cfg.num_experts)
    cap = max(cap, cfg.min_capacity)
    if not cfg.drop_tokens:
        cap = tokens_per_group  # worst case: everyone to one expert
    return min(cap, tokens_per_group * cfg.top_k)


def top_k_gating(logits: jax.Array, cfg: GateConfig, capacity: int
                 ) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Generalized top-k gate (covers the reference's top1/top2/topk).

    logits: [G, S, E] (G = groups = batch dim). Returns
    (combine_weights [G,S,E,C], dispatch_mask [G,S,E,C] bool, aux dict).
    """
    G, S, E = logits.shape
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [G,S,E]

    # per-k expert choice with positional priority (earlier tokens win
    # capacity slots, k=0 choices win over k=1 — reference topkgating's
    # sequential locations, sharded_moe.py:375)
    combine = jnp.zeros((G, S, E, capacity), jnp.float32)
    counts = jnp.zeros((G, E), jnp.int32)  # slots used per expert
    remaining = gates
    denom = jnp.zeros((G, S), jnp.float32)
    picks = []
    for _ in range(cfg.top_k):
        idx = jnp.argmax(remaining, axis=-1)  # [G,S]
        picks.append(idx)
        gate_val = jnp.take_along_axis(gates, idx[..., None], axis=-1)[..., 0]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [G,S,E]
        # position of each token within its chosen expert's slots: tokens
        # before me this round + slots used by earlier rounds
        pos_in_exp = jnp.cumsum(onehot, axis=1) - onehot  # [G,S,E]
        pos = (jnp.take_along_axis(pos_in_exp, idx[..., None], axis=-1)[..., 0]
               + jnp.take_along_axis(counts, idx, axis=1).astype(jnp.float32))
        keep = pos < capacity
        gate_kept = jnp.where(keep, gate_val, 0.0)
        denom = denom + gate_kept
        pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                dtype=jnp.float32)  # [G,S,C]
        combine = combine + (gate_kept[..., None, None]
                             * onehot[..., :, None] * pos_oh[..., None, :])
        counts = counts + jnp.sum(
            onehot * keep[..., None].astype(jnp.float32), axis=1).astype(jnp.int32)
        remaining = remaining * (1.0 - onehot)  # mask picked expert

    # normalize combine weights over the kept top-k gates (reference
    # normalizes top-k probs, sharded_moe.py topkgating)
    combine = combine / jnp.maximum(denom[..., None, None], 1e-9)
    dispatch = combine > 0.0

    # load-balancing aux loss: E * mean_e(frac_tokens_e * mean_gate_e)
    # (reference l_aux, sharded_moe.py:262)
    me = jnp.mean(gates, axis=(0, 1))  # [E]
    top1_onehot = jax.nn.one_hot(picks[0], E, dtype=jnp.float32)
    ce = jnp.mean(top1_onehot, axis=(0, 1))  # [E]
    l_aux = jnp.sum(me * ce) * E

    aux: Dict[str, jax.Array] = {"l_aux": l_aux}
    if cfg.z_loss_weight:
        zl = jnp.mean(jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1) ** 2)
        aux["l_zloss"] = zl
    # expert counts for observability (reference exp_counts)
    aux["expert_load"] = counts.astype(jnp.float32).mean(axis=0) / max(S, 1)
    return combine, dispatch, aux


def _grouped_unsupported_reason(cfg: GateConfig) -> Optional[str]:
    """Why the grouped path can't run on the current mesh (None = it can).

    The grouped engine composes with dp/fsdp (token-parallel shards), ep
    (experts partitioned per shard, tokens routed by two all-to-alls),
    sp (another token axis), tp (FFN dim split + deferred psum) and pp
    (the dispatch shard_map nests inside the pipeline's manual-pp stage
    body over the remaining auto axes). The one exclusion left: expert
    counts that don't divide over ep."""
    from deepspeed_tpu.parallel import topology as topo

    from deepspeed_tpu.runtime import sharding as _sharding

    mesh = topo._GLOBAL_MESH
    if mesh is None:
        return None
    ep = mesh.shape.get("ep", 1)
    if ep > 1 and cfg.num_experts % ep:
        return f"num_experts={cfg.num_experts} not divisible by ep={ep}"
    # the dispatch shard_map must manualize ep/tp itself (its collectives
    # and specs reference them); an enclosing region that already
    # manualized them (none in-tree does) can't host the grouped path
    pre_manual = sorted(a for a in ("ep", "tp")
                        if a in _sharding._MANUAL_AXES
                        and mesh.shape.get(a, 1) > 1)
    if pre_manual:
        return f"axes {pre_manual} already manual in the enclosing region"
    # under qgZ's per-group gradient vmap the token axes are mapped, not
    # mesh-sharded — a shard_map can't map a vmapped dim, so the einsum
    # dispatch (plain GSPMD ops, vmappable) carries MoE there. This is an
    # engine-internal trace mode, not a user mesh limit: soft (see
    # moe_ffn — even an explicit impl="grouped" degrades here instead of
    # raising, since the same config trains fine outside the qgZ vmap)
    vmapped = sorted(a for a in ("dp", "fsdp", "ep", "sp")
                     if a in getattr(_sharding, "_VMAPPED_AXES", frozenset())
                     and mesh.shape.get(a, 1) > 1)
    if vmapped:
        return (f"token axes {vmapped} are vmapped (qgZ per-group grads): "
                "grouped dispatch uses the einsum path [soft]")
    return None


def moe_ffn(x: jax.Array, router_w: jax.Array, expert_params: Dict[str, jax.Array],
            cfg: GateConfig, activation: str = "swiglu", train: bool = True,
            impl: str = "auto") -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Full MoE FFN block (reference MOELayer.forward sharded_moe.py:589).

    x: [B, S, H]; router_w: [H, E]; expert_params: wi/wo(/wg) with leading
    expert dim [E, ...] sharded over the ep mesh axis.

    impl: "einsum" = capacity-padded GShard dispatch (drops overflow
    tokens, pads underflow — fixed E*C flops); "grouped" = dropless
    grouped-GEMM execution (reference GroupedExperts, ep_experts.py:136 —
    exact top-k flops regardless of imbalance), expert-parallel over ep
    with two all-to-alls and tp-split FFNs (see moe_ffn_dropless).
    "auto"/"grouped" take the grouped path on every mesh (under pp the
    dispatch nests inside the pipeline stage body) except E % ep != 0 —
    "auto" falls back to einsum there with a telemetry count
    ("moe.grouped_fallback") and a one-time warning; an explicit
    "grouped" raises instead (a silent numeric change is worse than an
    error).
    """
    if impl in ("auto", "grouped"):
        reason = _grouped_unsupported_reason(cfg)
        if reason is None:
            return moe_ffn_dropless(x, router_w, expert_params, cfg,
                                    activation=activation, train=train)
        if impl == "grouped" and "[soft]" not in reason:
            # an explicit request must not silently change numerics (the
            # einsum path drops tokens differently); only "auto" degrades.
            # Exception: [soft] reasons are engine-internal trace modes
            # (the qgZ per-group vmap) — raising would make a valid user
            # config crash only when qgZ arms, so those degrade with
            # telemetry for explicit "grouped" too.
            raise ValueError(
                f"moe_ffn: impl='grouped' is unsupported on this mesh: "
                f"{reason} (use impl='auto' to allow the einsum fallback)")
        from deepspeed_tpu.utils import telemetry
        telemetry.count("moe.grouped_fallback", reason)
    B, S, H = x.shape
    dt = x.dtype
    logits = jnp.einsum("bsh,he->bse", x, router_w.astype(dt))
    capacity = compute_capacity(S, cfg, train=train)
    combine, dispatch, aux = top_k_gating(logits, cfg, capacity)

    # dispatch: [B,S,H] x [B,S,E,C] -> [B,E,C,H]; constraining the E dim
    # onto ep makes GSPMD emit all-to-all #1 (reference _AllToAll
    # sharded_moe.py:97)
    dispatched = jnp.einsum("bsh,bsec->bech", x, dispatch.astype(dt))
    dispatched = constrain_activation(dispatched, ("batch", "expert", None, "embed"))

    wi, wo = expert_params["wi"].astype(dt), expert_params["wo"].astype(dt)
    if activation == "swiglu":
        wg = expert_params["wg"].astype(dt)
        gate = jnp.einsum("bech,ehf->becf", dispatched, wg)
        up = jnp.einsum("bech,ehf->becf", dispatched, wi)
        hidden = jax.nn.silu(gate) * up
    else:
        hidden = jax.nn.gelu(jnp.einsum("bech,ehf->becf", dispatched, wi))
    hidden = constrain_activation(hidden, ("batch", "expert", None, "mlp"))
    expert_out = jnp.einsum("becf,efh->bech", hidden, wo)

    # combine: all-to-all #2 back to token layout
    out = jnp.einsum("bech,bsec->bsh", expert_out,
                     combine.astype(dt))
    out = constrain_activation(out, ("batch", "seq", "embed"))
    return out, aux


def _expert_ffn(sorted_x: jax.Array, group_sizes: jax.Array,
                expert_params: Dict[str, jax.Array], activation: str,
                dt) -> jax.Array:
    """Grouped-GEMM expert FFN over rows sorted by (local) expert."""
    import functools

    from deepspeed_tpu.ops import attention as attn_ops
    from deepspeed_tpu.ops.pallas.grouped_matmul import gmm as gmm_raw

    # engine-installed tile geometry (config.kernels.gmm_block_{m,n,k});
    # gmm snaps each to the largest legal divisor per operand shape
    gmm = functools.partial(gmm_raw, **attn_ops.kernel_gmm_tiles())
    wi, wo = expert_params["wi"].astype(dt), expert_params["wo"].astype(dt)
    if activation == "swiglu":
        wg = expert_params["wg"].astype(dt)
        hidden = jax.nn.silu(gmm(sorted_x, wg, group_sizes)) \
            * gmm(sorted_x, wi, group_sizes)
    else:
        hidden = jax.nn.gelu(gmm(sorted_x, wi, group_sizes))
    return gmm(hidden, wo, group_sizes)                     # [M, H-or-H_tp]


def _ep_capacity(m0: int, ep: int, cfg: GateConfig, train: bool) -> int:
    """Static per-(src,dst) row budget for the expert all-to-all.

    drop_tokens=False → the true worst case (every local row to one
    owner shard): genuinely dropless, at ep× the balanced buffer. With
    drop_tokens, capacity pools at *shard* level (an owner's hot expert
    borrows headroom from its cold co-residents — strictly fewer drops
    than the reference's per-expert capacity at the same factor,
    sharded_moe.py:91)."""
    if cfg.drop_tokens:
        factor = cfg.capacity_factor if train else cfg.eval_capacity_factor
        cap = int(-(-factor * m0 // ep))                    # ceil
        cap = max(cap, cfg.min_capacity)
        cap = min(cap, m0)
    else:
        cap = m0
    return ((cap + 127) // 128) * 128                       # MXU row tile


def _dropless_shard_core(x: jax.Array, router_w: jax.Array,
                         expert_params: Dict[str, jax.Array],
                         cfg: GateConfig, activation: str, *,
                         ep_axis: Optional[str] = None, ep: int = 1,
                         tp_axis: Optional[str] = None, tp: int = 1,
                         train: bool = True
                         ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Per-shard dropless dispatch (runs inside shard_map, or bare when
    there is no mesh).

    ep == 1: rows sort by expert locally and run through the whole
    (locally resident) expert stack — the original single-shard engine.

    ep > 1: *expert parallelism*. ``expert_params`` hold only this
    shard's E/ep experts; each row's owner shard is ``expert // e_loc``
    and rows travel by two all-to-alls over ``ep_axis`` (the reference's
    dispatch/combine pair, sharded_moe.py:589-685) with a static
    per-(src,dst) row budget (:func:`_ep_capacity`). Overflow rows are
    dropped at the sender with zero combine weight and counted in
    ``stats['ep_dropped_frac']``.

    tp > 1: ``expert_params`` additionally hold only this shard's F/tp
    slice of every expert; the combine output is a partial sum and is
    psum'd over ``tp_axis`` at the end (deferred past the return
    all-to-all — [tokens,H] is top_k× smaller than the row buffer). A
    routing digest cross-checks that all tp peers dispatched
    identically (reference TP-consistency digests, ep_tp_dispatch.py:99).

    Stats are shaped so an unweighted mean over equal-sized token shards
    reproduces the global statistic exactly.
    """
    B, S, H = x.shape
    E, k = cfg.num_experts, cfg.top_k
    e_loc = E // ep
    # the expert-parallel guarantee, enforced at trace time: a shard only
    # ever holds E/ep experts (no whole-stack gather can have happened)
    assert expert_params["wi"].shape[0] == e_loc, (
        f"expected {e_loc} experts per ep shard, got "
        f"{expert_params['wi'].shape[0]}")
    dt = x.dtype
    logits = jnp.einsum("bsh,he->bse", x, router_w.astype(dt))
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_vals, top_idx = lax.top_k(gates, k)
    weights = top_vals / jnp.maximum(
        jnp.sum(top_vals, axis=-1, keepdims=True), 1e-9)

    tokens = B * S
    m0 = tokens * k
    flat_x = x.reshape(tokens, H)
    flat_expert = top_idx.reshape(-1).astype(jnp.int32)     # [m0]
    flat_w = weights.reshape(-1)                            # fp32
    token_idx = jnp.repeat(jnp.arange(tokens, dtype=jnp.int32), k)

    stats = {
        "me": jnp.mean(gates, axis=(0, 1)),                          # [E]
        "ce": jnp.mean(jax.nn.one_hot(top_idx[..., 0], E,
                                      dtype=jnp.float32), axis=(0, 1)),
        "zsq": jnp.mean(jax.nn.logsumexp(
            logits.astype(jnp.float32), axis=-1) ** 2)[None],
        "expert_load": (jnp.bincount(flat_expert, length=E)
                        .astype(jnp.float32) / max(tokens, 1)),
        "ep_dropped_frac": jnp.zeros((1,), jnp.float32),
        "dispatch_digest_mismatch": jnp.zeros((1,), jnp.float32),
    }
    if tp > 1:
        # dispatch digest: order-sensitive checksum of the routing
        # decision; pmax==pmin over tp ⇔ every tp peer will slice the
        # same rows to the same experts (they see replicated x, so any
        # mismatch means nondeterminism that would corrupt the deferred
        # psum row alignment)
        dig = jnp.sum(flat_expert.astype(jnp.uint32)
                      * (jnp.arange(m0, dtype=jnp.uint32)
                         * jnp.uint32(2654435761) + jnp.uint32(12345)))
        mismatch = lax.pmax(dig, tp_axis) != lax.pmin(dig, tp_axis)
        stats["dispatch_digest_mismatch"] = \
            mismatch.astype(jnp.float32)[None]

    if ep > 1:
        dest = flat_expert // e_loc                         # owner shard
        cap = _ep_capacity(m0, ep, cfg, train)
        # position of row j within its (src→dest) budget: rows fill
        # slots in row order
        oh = (dest[:, None] == jnp.arange(ep, dtype=jnp.int32)[None, :]
              ).astype(jnp.int32)
        pos = jnp.take_along_axis(jnp.cumsum(oh, axis=0) - oh,
                                  dest[:, None], axis=1)[:, 0]
        keep = pos < cap
        pos_c = jnp.minimum(pos, cap - 1)
        kf = keep.astype(dt)
        # renormalize combine weights over *kept* gates per token (the
        # einsum path and reference topkgating normalize over kept top-k
        # probs; without this a token whose row overflowed the budget
        # would lose that weight mass entirely instead of redistributing
        # it to its surviving experts)
        keep_f = keep.astype(jnp.float32)
        kept_mass = jnp.zeros((tokens,), jnp.float32).at[token_idx].add(
            flat_w * keep_f)
        flat_w = flat_w * keep_f / jnp.maximum(kept_mass[token_idx], 1e-9)
        rows_x = flat_x[token_idx]                          # [m0, H]
        # packed send buffers: [ep*cap, H] rows + [ep*cap] local-expert
        # tags (0 = padding slot); kept slots are unique so scatter-add
        # is exact, dropped rows add zeros into the clamped last slot
        send_x = jnp.zeros((ep * cap, H), dt).at[
            dest * cap + pos_c].add(rows_x * kf[:, None])
        tag = (flat_expert % e_loc + 1) * keep
        send_tag = jnp.zeros((ep * cap,), jnp.int32).at[
            dest * cap + pos_c].add(tag)
        # all-to-all #1 (dispatch): block d of mine → shard d; block s
        # of the result ← shard s's rows for my experts
        recv_x = lax.all_to_all(send_x, ep_axis, 0, 0, tiled=True)
        recv_tag = lax.all_to_all(send_tag, ep_axis, 0, 0, tiled=True)

        m_rows = ep * cap
        valid = recv_tag > 0
        local_e = jnp.where(valid, recv_tag - 1, e_loc - 1)
        order = jnp.argsort(local_e, stable=True)
        sorted_x = recv_x[order]
        group_sizes = jnp.bincount(local_e, length=e_loc).astype(jnp.int32)
        expert_out = _expert_ffn(sorted_x, group_sizes, expert_params,
                                 activation, dt)            # [m_rows, H]
        unsorted = jnp.zeros((m_rows, H), dt).at[order].set(expert_out)
        # all-to-all #2 (combine): results return to their source shard
        back = lax.all_to_all(unsorted, ep_axis, 0, 0, tiled=True)
        out_rows = back[dest * cap + pos_c] * kf[:, None]   # [m0, H]
        contrib = out_rows.astype(jnp.float32) * flat_w[:, None]
        stats["ep_dropped_frac"] = (
            jnp.sum(~keep).astype(jnp.float32) / max(m0, 1))[None]
        row_token = token_idx
    else:
        # local sort path: pad rows to the MXU tile; padding rows carry
        # zero combine weight and land in the last group
        m = ((m0 + 127) // 128) * 128
        pad = m - m0
        if pad:
            flat_expert = jnp.concatenate(
                [flat_expert, jnp.full((pad,), E - 1, flat_expert.dtype)])
            flat_w = jnp.concatenate([flat_w, jnp.zeros((pad,), flat_w.dtype)])
            token_idx = jnp.concatenate(
                [token_idx, jnp.zeros((pad,), token_idx.dtype)])
        order = jnp.argsort(flat_expert, stable=True)       # [M]
        row_token = token_idx[order]
        flat_w = flat_w[order]
        group_sizes = jnp.bincount(flat_expert, length=E).astype(jnp.int32)
        sorted_x = flat_x[row_token]                        # [M, H] gather
        expert_out = _expert_ffn(sorted_x, group_sizes, expert_params,
                                 activation, dt)
        contrib = expert_out.astype(jnp.float32) * flat_w[:, None]

    # combine accumulates in fp32 (bf16 scatter-add would stack rounding
    # per top-k contribution); one cast back at the end
    out = jnp.zeros((tokens, H), jnp.float32).at[row_token].add(contrib)
    if tp > 1:
        out = lax.psum(out, tp_axis)                        # F/tp partials
    out = out.astype(dt).reshape(B, S, H)
    return out, stats


def _aux_from_stats(stats: Dict[str, jax.Array], cfg: GateConfig
                    ) -> Dict[str, jax.Array]:
    """Same aux-loss formulas as top_k_gating, from (globally averaged)
    routing statistics."""
    E = cfg.num_experts
    aux = {"l_aux": jnp.sum(stats["me"] * stats["ce"]) * E,
           "expert_load": stats["expert_load"]}
    if cfg.z_loss_weight:
        aux["l_zloss"] = stats["zsq"][0]
    for key in ("ep_dropped_frac", "dispatch_digest_mismatch"):
        if key in stats:
            aux[key] = stats[key][0]
    return aux


_STAT_KEYS = ("me", "ce", "zsq", "expert_load", "ep_dropped_frac",
              "dispatch_digest_mismatch")


def moe_ffn_dropless(x: jax.Array, router_w: jax.Array,
                     expert_params: Dict[str, jax.Array], cfg: GateConfig,
                     activation: str = "swiglu", train: bool = True
                     ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Dropless MoE FFN via grouped GEMMs (reference GroupedExperts,
    moe/ep_experts.py:136, executed through the two-all-to-all structure
    of MOELayer.forward, sharded_moe.py:589-685).

    Tokens sort by chosen expert (stable argsort keeps static shapes),
    experts execute as one grouped matmul per projection
    (ops/pallas/grouped_matmul.py), and outputs scatter-add back weighted
    by the gate — exactly top_k expert-FFNs per token with no capacity
    padding, flops independent of routing imbalance.

    Mesh composition (a Pallas call can't be GSPMD-partitioned, so the
    whole dispatch runs inside one shard_map):

      dp/fsdp/sp  token axes — each shard routes its own tokens.
      ep          experts *partition* over the axis (in_spec P('ep') on
                  the stacked expert dim: a shard only ever sees E/ep
                  experts — no whole-stack gather); tokens travel to
                  their owner shard and back via two all-to-alls.
      tp          every expert's FFN dim splits over tp (in_spec on the
                  mlp dim); the combine is psum'd over tp, and routing
                  digests assert tp peers dispatched identically.
      fsdp        the ZeRO-3 param fetch: the expert in_spec leaves the
                  embed dim unsharded, so GSPMD all-gathers it over fsdp
                  on use (stage-3 semantics, never over ep).
      pp          when called inside the pipeline's manual-pp stage body
                  (runtime/sharding.manual_axes tracks it), the dispatch
                  shard_map nests: it takes the context *abstract* mesh
                  and manualizes only the still-auto axes, so the two
                  all-to-alls and the tp psum run per pipeline stage —
                  the reference's MoE-inside-pipe composition
                  (sharded_moe.py:589 under runtime/pipe/engine.py:60).
    """
    from deepspeed_tpu.parallel import topology as topo
    from deepspeed_tpu.runtime import sharding as _sharding

    mesh = topo._GLOBAL_MESH
    manual = _sharding._MANUAL_AXES
    sizes = dict(mesh.shape) if mesh is not None else {}
    ep, tp = sizes.get("ep", 1), sizes.get("tp", 1)
    B_in, S_in = x.shape[0], x.shape[1]
    # token axes only shard what divides: a serve-time batch of 2 on a
    # dp=2×ep=2 mesh shards over dp and *replicates* over ep — the ep
    # dispatch still partitions experts and routes correctly (each source
    # gets its own copies back), it just computes redundantly across the
    # unused token axis. Axes already manual in an enclosing region (the
    # pipeline's pp, the ZeRO++ dp region) can't be re-manualized or
    # referenced in this shard_map's specs — they drop out of the token
    # axes (the enclosing region already localized them).
    def _auto(a: str) -> int:
        return 1 if a in manual else sizes.get(a, 1)

    batch_axes, prod = [], 1
    for a in ("dp", "fsdp", "ep"):
        sz = _auto(a)
        if sz > 1 and B_in % (prod * sz) == 0:
            batch_axes.append(a)
            prod *= sz
    batch_axes = tuple(batch_axes)
    sp = _auto("sp") if S_in % max(_auto("sp"), 1) == 0 else 1
    # token axes the batch dim can't absorb fall through to the sequence
    # dim: routing is per-token, so a batch of 1 still shards its S
    # tokens over ep/dp/fsdp (the dryrun's B=1,S=32,ep=2 case) instead
    # of replicating the whole dispatch on every ep shard
    seq_axes, sprod = [], max(sp, 1)
    for a in ("dp", "fsdp", "ep"):
        sz = _auto(a)
        if sz > 1 and a not in batch_axes and S_in % (sprod * sz) == 0:
            seq_axes.append(a)
            sprod *= sz
    seq_axes = tuple(seq_axes)
    placed = set(batch_axes) | set(seq_axes)
    if mesh is not None and (
            any(_auto(a) > 1 and a not in placed
                for a in ("dp", "fsdp", "ep"))
            or sp != _auto("sp")):
        from deepspeed_tpu.utils import telemetry
        telemetry.count(
            "moe.grouped_replicated_tokens",
            f"batch {B_in}x{S_in} not shardable over all token axes "
            f"{ {a: sizes.get(a, 1) for a in ('dp', 'fsdp', 'ep', 'sp')} }")
    if mesh is None or (not batch_axes and not seq_axes
                        and tp == 1 and sp == 1 and ep == 1):
        out, stats = _dropless_shard_core(x, router_w, expert_params, cfg,
                                          activation, train=train)
        out = constrain_activation(out, ("batch", "seq", "embed"))
        return out, _aux_from_stats(stats, cfg)

    if ep > 1 and cfg.num_experts % ep:
        raise ValueError(
            f"moe_ffn_dropless: num_experts={cfg.num_experts} must divide "
            f"over ep={ep}")

    from jax.sharding import PartitionSpec as P

    ep_ax = "ep" if ep > 1 else None
    tp_ax = "tp" if tp > 1 else None
    sp_ax = "sp" if sp > 1 else None
    seq_entry = seq_axes + ((sp_ax,) if sp_ax else ())
    token_axes = batch_axes + seq_entry

    def local_fn(x, router_w, experts):
        out, stats = _dropless_shard_core(
            x, router_w, experts, cfg, activation,
            ep_axis=ep_ax, ep=ep, tp_axis=tp_ax, tp=tp, train=train)
        return out, jax.tree.map(lambda s: s[None], stats)  # lead shard dim

    x_spec = P(batch_axes or None, seq_entry or None, None)
    # stacked experts: expert dim stays on ep, mlp dim on tp, embed dim
    # gathered (the ZeRO-3 fetch — over fsdp only)
    exp_specs = {"wi": P(ep_ax, None, tp_ax), "wo": P(ep_ax, tp_ax, None)}
    if "wg" in expert_params:
        exp_specs["wg"] = P(ep_ax, None, tp_ax)
    stat_spec = {k: P(token_axes or None) for k in _STAT_KEYS}
    if manual:
        # nested inside a partial-manual region (the pipeline stage body
        # is manual over pp): shard_map must take the context abstract
        # mesh and may only manualize the axes still under GSPMD
        sm_mesh = jaxcompat.get_abstract_mesh(fallback=mesh)
    else:
        sm_mesh = mesh
    names = frozenset(a for a in mesh.axis_names if a not in manual)
    out, stats_sh = jaxcompat.shard_map(
        local_fn, mesh=sm_mesh,
        in_specs=(x_spec, P(), exp_specs),
        out_specs=(x_spec, stat_spec), axis_names=names, check_vma=False,
    )(x, router_w, expert_params)
    stats = jax.tree.map(lambda s: jnp.mean(s, axis=0), stats_sh)
    out = constrain_activation(out, ("batch", "seq", "embed"))
    return out, _aux_from_stats(stats, cfg)
