"""Mixture-of-Experts: top-k gating + expert-parallel dispatch.

Reference: deepspeed/moe/sharded_moe.py — ``top1gating`` :184,
``top2gating`` :291, ``topkgating`` :375, ``MOELayer.forward`` :589-685
(einsum dispatch, two all-to-alls around local experts), aux
load-balancing losses; expert groups deepspeed/utils/groups.py:304.

TPU-native shape: the dispatch/combine tensors are einsums (exactly the
GShard formulation the reference follows), and the "two all-to-alls" are
not explicit calls — expert weights shard over the ``ep`` mesh axis and
the dispatched activations get a sharding constraint onto ``ep``, so
GSPMD emits the token all-to-all pair on ICI. Capacity-style static
shapes keep everything jit-compatible (no ragged dispatch in the train
path; ragged decode lives in the inference stack).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.runtime.sharding import constrain_activation


@dataclasses.dataclass(frozen=True)
class GateConfig:
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    drop_tokens: bool = True
    aux_loss_weight: float = 0.01
    z_loss_weight: float = 0.0


def compute_capacity(tokens_per_group: int, cfg: GateConfig,
                     train: bool = True) -> int:
    """Reference _capacity (sharded_moe.py:91)."""
    factor = cfg.capacity_factor if train else cfg.eval_capacity_factor
    cap = int(tokens_per_group * factor * cfg.top_k / cfg.num_experts)
    cap = max(cap, cfg.min_capacity)
    if not cfg.drop_tokens:
        cap = tokens_per_group  # worst case: everyone to one expert
    return min(cap, tokens_per_group * cfg.top_k)


def top_k_gating(logits: jax.Array, cfg: GateConfig, capacity: int
                 ) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Generalized top-k gate (covers the reference's top1/top2/topk).

    logits: [G, S, E] (G = groups = batch dim). Returns
    (combine_weights [G,S,E,C], dispatch_mask [G,S,E,C] bool, aux dict).
    """
    G, S, E = logits.shape
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [G,S,E]

    # per-k expert choice with positional priority (earlier tokens win
    # capacity slots, k=0 choices win over k=1 — reference topkgating's
    # sequential locations, sharded_moe.py:375)
    combine = jnp.zeros((G, S, E, capacity), jnp.float32)
    counts = jnp.zeros((G, E), jnp.int32)  # slots used per expert
    remaining = gates
    denom = jnp.zeros((G, S), jnp.float32)
    picks = []
    for _ in range(cfg.top_k):
        idx = jnp.argmax(remaining, axis=-1)  # [G,S]
        picks.append(idx)
        gate_val = jnp.take_along_axis(gates, idx[..., None], axis=-1)[..., 0]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [G,S,E]
        # position of each token within its chosen expert's slots: tokens
        # before me this round + slots used by earlier rounds
        pos_in_exp = jnp.cumsum(onehot, axis=1) - onehot  # [G,S,E]
        pos = (jnp.take_along_axis(pos_in_exp, idx[..., None], axis=-1)[..., 0]
               + jnp.take_along_axis(counts, idx, axis=1).astype(jnp.float32))
        keep = pos < capacity
        gate_kept = jnp.where(keep, gate_val, 0.0)
        denom = denom + gate_kept
        pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                dtype=jnp.float32)  # [G,S,C]
        combine = combine + (gate_kept[..., None, None]
                             * onehot[..., :, None] * pos_oh[..., None, :])
        counts = counts + jnp.sum(
            onehot * keep[..., None].astype(jnp.float32), axis=1).astype(jnp.int32)
        remaining = remaining * (1.0 - onehot)  # mask picked expert

    # normalize combine weights over the kept top-k gates (reference
    # normalizes top-k probs, sharded_moe.py topkgating)
    combine = combine / jnp.maximum(denom[..., None, None], 1e-9)
    dispatch = combine > 0.0

    # load-balancing aux loss: E * mean_e(frac_tokens_e * mean_gate_e)
    # (reference l_aux, sharded_moe.py:262)
    me = jnp.mean(gates, axis=(0, 1))  # [E]
    top1_onehot = jax.nn.one_hot(picks[0], E, dtype=jnp.float32)
    ce = jnp.mean(top1_onehot, axis=(0, 1))  # [E]
    l_aux = jnp.sum(me * ce) * E

    aux: Dict[str, jax.Array] = {"l_aux": l_aux}
    if cfg.z_loss_weight:
        zl = jnp.mean(jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1) ** 2)
        aux["l_zloss"] = zl
    # expert counts for observability (reference exp_counts)
    aux["expert_load"] = counts.astype(jnp.float32).mean(axis=0) / max(S, 1)
    return combine, dispatch, aux


def moe_ffn(x: jax.Array, router_w: jax.Array, expert_params: Dict[str, jax.Array],
            cfg: GateConfig, activation: str = "swiglu", train: bool = True
            ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Full MoE FFN block (reference MOELayer.forward sharded_moe.py:589).

    x: [B, S, H]; router_w: [H, E]; expert_params: wi/wo(/wg) with leading
    expert dim [E, ...] sharded over the ep mesh axis.
    """
    B, S, H = x.shape
    dt = x.dtype
    logits = jnp.einsum("bsh,he->bse", x, router_w.astype(dt))
    capacity = compute_capacity(S, cfg, train=train)
    combine, dispatch, aux = top_k_gating(logits, cfg, capacity)

    # dispatch: [B,S,H] x [B,S,E,C] -> [B,E,C,H]; constraining the E dim
    # onto ep makes GSPMD emit all-to-all #1 (reference _AllToAll
    # sharded_moe.py:97)
    dispatched = jnp.einsum("bsh,bsec->bech", x, dispatch.astype(dt))
    dispatched = constrain_activation(dispatched, ("batch", "expert", None, "embed"))

    wi, wo = expert_params["wi"].astype(dt), expert_params["wo"].astype(dt)
    if activation == "swiglu":
        wg = expert_params["wg"].astype(dt)
        gate = jnp.einsum("bech,ehf->becf", dispatched, wg)
        up = jnp.einsum("bech,ehf->becf", dispatched, wi)
        hidden = jax.nn.silu(gate) * up
    else:
        hidden = jax.nn.gelu(jnp.einsum("bech,ehf->becf", dispatched, wi))
    hidden = constrain_activation(hidden, ("batch", "expert", None, "mlp"))
    expert_out = jnp.einsum("becf,efh->bech", hidden, wo)

    # combine: all-to-all #2 back to token layout
    out = jnp.einsum("bech,bsec->bsh", expert_out,
                     combine.astype(dt))
    out = constrain_activation(out, ("batch", "seq", "embed"))
    return out, aux
