"""Mixture-of-Experts: top-k gating + expert-parallel dispatch.

Reference: deepspeed/moe/sharded_moe.py — ``top1gating`` :184,
``top2gating`` :291, ``topkgating`` :375, ``MOELayer.forward`` :589-685
(einsum dispatch, two all-to-alls around local experts), aux
load-balancing losses; expert groups deepspeed/utils/groups.py:304.

TPU-native shape: the dispatch/combine tensors are einsums (exactly the
GShard formulation the reference follows), and the "two all-to-alls" are
not explicit calls — expert weights shard over the ``ep`` mesh axis and
the dispatched activations get a sharding constraint onto ``ep``, so
GSPMD emits the token all-to-all pair on ICI. Capacity-style static
shapes keep everything jit-compatible (no ragged dispatch in the train
path; ragged decode lives in the inference stack).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.runtime.sharding import constrain_activation


@dataclasses.dataclass(frozen=True)
class GateConfig:
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    drop_tokens: bool = True
    aux_loss_weight: float = 0.01
    z_loss_weight: float = 0.0


def compute_capacity(tokens_per_group: int, cfg: GateConfig,
                     train: bool = True) -> int:
    """Reference _capacity (sharded_moe.py:91)."""
    factor = cfg.capacity_factor if train else cfg.eval_capacity_factor
    cap = int(tokens_per_group * factor * cfg.top_k / cfg.num_experts)
    cap = max(cap, cfg.min_capacity)
    if not cfg.drop_tokens:
        cap = tokens_per_group  # worst case: everyone to one expert
    return min(cap, tokens_per_group * cfg.top_k)


def top_k_gating(logits: jax.Array, cfg: GateConfig, capacity: int
                 ) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Generalized top-k gate (covers the reference's top1/top2/topk).

    logits: [G, S, E] (G = groups = batch dim). Returns
    (combine_weights [G,S,E,C], dispatch_mask [G,S,E,C] bool, aux dict).
    """
    G, S, E = logits.shape
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [G,S,E]

    # per-k expert choice with positional priority (earlier tokens win
    # capacity slots, k=0 choices win over k=1 — reference topkgating's
    # sequential locations, sharded_moe.py:375)
    combine = jnp.zeros((G, S, E, capacity), jnp.float32)
    counts = jnp.zeros((G, E), jnp.int32)  # slots used per expert
    remaining = gates
    denom = jnp.zeros((G, S), jnp.float32)
    picks = []
    for _ in range(cfg.top_k):
        idx = jnp.argmax(remaining, axis=-1)  # [G,S]
        picks.append(idx)
        gate_val = jnp.take_along_axis(gates, idx[..., None], axis=-1)[..., 0]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [G,S,E]
        # position of each token within its chosen expert's slots: tokens
        # before me this round + slots used by earlier rounds
        pos_in_exp = jnp.cumsum(onehot, axis=1) - onehot  # [G,S,E]
        pos = (jnp.take_along_axis(pos_in_exp, idx[..., None], axis=-1)[..., 0]
               + jnp.take_along_axis(counts, idx, axis=1).astype(jnp.float32))
        keep = pos < capacity
        gate_kept = jnp.where(keep, gate_val, 0.0)
        denom = denom + gate_kept
        pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                dtype=jnp.float32)  # [G,S,C]
        combine = combine + (gate_kept[..., None, None]
                             * onehot[..., :, None] * pos_oh[..., None, :])
        counts = counts + jnp.sum(
            onehot * keep[..., None].astype(jnp.float32), axis=1).astype(jnp.int32)
        remaining = remaining * (1.0 - onehot)  # mask picked expert

    # normalize combine weights over the kept top-k gates (reference
    # normalizes top-k probs, sharded_moe.py topkgating)
    combine = combine / jnp.maximum(denom[..., None, None], 1e-9)
    dispatch = combine > 0.0

    # load-balancing aux loss: E * mean_e(frac_tokens_e * mean_gate_e)
    # (reference l_aux, sharded_moe.py:262)
    me = jnp.mean(gates, axis=(0, 1))  # [E]
    top1_onehot = jax.nn.one_hot(picks[0], E, dtype=jnp.float32)
    ce = jnp.mean(top1_onehot, axis=(0, 1))  # [E]
    l_aux = jnp.sum(me * ce) * E

    aux: Dict[str, jax.Array] = {"l_aux": l_aux}
    if cfg.z_loss_weight:
        zl = jnp.mean(jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1) ** 2)
        aux["l_zloss"] = zl
    # expert counts for observability (reference exp_counts)
    aux["expert_load"] = counts.astype(jnp.float32).mean(axis=0) / max(S, 1)
    return combine, dispatch, aux


def _grouped_ok() -> bool:
    """Dropless grouped-GEMM path composes with dp/fsdp batch sharding
    (a shard_map over the batch axes — each shard routes its own tokens,
    expert weights gather whole per shard, the ZeRO-3 fetch semantic)
    but not yet with expert/tensor/sequence model sharding — those fall
    back to the capacity einsum dispatch whose all-to-alls GSPMD
    partitions."""
    from deepspeed_tpu.parallel import topology as topo

    mesh = topo._GLOBAL_MESH
    if mesh is None:
        return True
    return all(mesh.shape.get(a, 1) == 1 for a in ("ep", "tp", "sp", "pp"))


def moe_ffn(x: jax.Array, router_w: jax.Array, expert_params: Dict[str, jax.Array],
            cfg: GateConfig, activation: str = "swiglu", train: bool = True,
            impl: str = "auto") -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Full MoE FFN block (reference MOELayer.forward sharded_moe.py:589).

    x: [B, S, H]; router_w: [H, E]; expert_params: wi/wo(/wg) with leading
    expert dim [E, ...] sharded over the ep mesh axis.

    impl: "einsum" = capacity-padded GShard dispatch (drops overflow
    tokens, pads underflow — fixed E*C flops); "grouped" = dropless
    grouped-GEMM execution (reference GroupedExperts, ep_experts.py:136 —
    exact top-k flops regardless of imbalance); "auto" picks grouped
    whenever the mesh doesn't shard experts/tp/sp.
    """
    if impl == "auto":
        impl = "grouped" if _grouped_ok() else "einsum"
    if impl == "grouped":
        return moe_ffn_dropless(x, router_w, expert_params, cfg,
                                activation=activation, train=train)
    B, S, H = x.shape
    dt = x.dtype
    logits = jnp.einsum("bsh,he->bse", x, router_w.astype(dt))
    capacity = compute_capacity(S, cfg, train=train)
    combine, dispatch, aux = top_k_gating(logits, cfg, capacity)

    # dispatch: [B,S,H] x [B,S,E,C] -> [B,E,C,H]; constraining the E dim
    # onto ep makes GSPMD emit all-to-all #1 (reference _AllToAll
    # sharded_moe.py:97)
    dispatched = jnp.einsum("bsh,bsec->bech", x, dispatch.astype(dt))
    dispatched = constrain_activation(dispatched, ("batch", "expert", None, "embed"))

    wi, wo = expert_params["wi"].astype(dt), expert_params["wo"].astype(dt)
    if activation == "swiglu":
        wg = expert_params["wg"].astype(dt)
        gate = jnp.einsum("bech,ehf->becf", dispatched, wg)
        up = jnp.einsum("bech,ehf->becf", dispatched, wi)
        hidden = jax.nn.silu(gate) * up
    else:
        hidden = jax.nn.gelu(jnp.einsum("bech,ehf->becf", dispatched, wi))
    hidden = constrain_activation(hidden, ("batch", "expert", None, "mlp"))
    expert_out = jnp.einsum("becf,efh->bech", hidden, wo)

    # combine: all-to-all #2 back to token layout
    out = jnp.einsum("bech,bsec->bsh", expert_out,
                     combine.astype(dt))
    out = constrain_activation(out, ("batch", "seq", "embed"))
    return out, aux


def _dropless_core(x: jax.Array, router_w: jax.Array,
                   expert_params: Dict[str, jax.Array], cfg: GateConfig,
                   activation: str) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Single-shard dropless dispatch. Returns (out, per-shard stats);
    stats are shaped so that an unweighted mean over equal-sized shards
    reproduces the global statistic exactly (me/ce/zsq/expert_load are
    all means over local tokens)."""
    from deepspeed_tpu.ops.pallas.grouped_matmul import gmm

    B, S, H = x.shape
    E, k = cfg.num_experts, cfg.top_k
    dt = x.dtype
    logits = jnp.einsum("bsh,he->bse", x, router_w.astype(dt))
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_vals, top_idx = lax.top_k(gates, k)
    weights = top_vals / jnp.maximum(
        jnp.sum(top_vals, axis=-1, keepdims=True), 1e-9)

    tokens = B * S
    flat_x = x.reshape(tokens, H)
    flat_expert = top_idx.reshape(-1)                       # [tokens*k]
    flat_w = weights.reshape(-1)
    token_idx = jnp.repeat(jnp.arange(tokens, dtype=jnp.int32), k)

    # pad the row count to the 128-row MXU tile; padding rows carry zero
    # combine weight and point at token 0, so they can run through any
    # expert (assign E-1: real rows already sum to group_sizes, padding
    # lands in the last group)
    m0 = tokens * k
    m = ((m0 + 127) // 128) * 128
    pad = m - m0
    if pad:
        flat_expert = jnp.concatenate(
            [flat_expert, jnp.full((pad,), E - 1, flat_expert.dtype)])
        flat_w = jnp.concatenate([flat_w, jnp.zeros((pad,), flat_w.dtype)])
        token_idx = jnp.concatenate(
            [token_idx, jnp.zeros((pad,), token_idx.dtype)])

    order = jnp.argsort(flat_expert, stable=True)           # [M]
    sorted_token = token_idx[order]
    sorted_w = flat_w[order]
    group_sizes = jnp.bincount(flat_expert, length=E).astype(jnp.int32)

    sorted_x = flat_x[sorted_token]                         # [M, H] gather

    wi, wo = expert_params["wi"].astype(dt), expert_params["wo"].astype(dt)
    if activation == "swiglu":
        wg = expert_params["wg"].astype(dt)
        hidden = jax.nn.silu(gmm(sorted_x, wg, group_sizes)) \
            * gmm(sorted_x, wi, group_sizes)
    else:
        hidden = jax.nn.gelu(gmm(sorted_x, wi, group_sizes))
    expert_out = gmm(hidden, wo, group_sizes)               # [M, H]

    contrib = expert_out * sorted_w[:, None].astype(dt)
    out = jnp.zeros((tokens, H), dt).at[sorted_token].add(contrib)
    out = out.reshape(B, S, H)

    stats = {
        "me": jnp.mean(gates, axis=(0, 1)),                          # [E]
        "ce": jnp.mean(jax.nn.one_hot(top_idx[..., 0], E,
                                      dtype=jnp.float32), axis=(0, 1)),
        "zsq": jnp.mean(jax.nn.logsumexp(
            logits.astype(jnp.float32), axis=-1) ** 2)[None],
        "expert_load": (jnp.bincount(top_idx.reshape(-1), length=E)
                        .astype(jnp.float32) / max(tokens, 1)),
    }
    return out, stats


def _aux_from_stats(stats: Dict[str, jax.Array], cfg: GateConfig
                    ) -> Dict[str, jax.Array]:
    """Same aux-loss formulas as top_k_gating, from (globally averaged)
    routing statistics."""
    E = cfg.num_experts
    aux = {"l_aux": jnp.sum(stats["me"] * stats["ce"]) * E,
           "expert_load": stats["expert_load"]}
    if cfg.z_loss_weight:
        aux["l_zloss"] = stats["zsq"][0]
    return aux


def moe_ffn_dropless(x: jax.Array, router_w: jax.Array,
                     expert_params: Dict[str, jax.Array], cfg: GateConfig,
                     activation: str = "swiglu", train: bool = True
                     ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Dropless MoE FFN via grouped GEMMs (reference GroupedExperts,
    moe/ep_experts.py:136).

    Tokens sort by chosen expert (stable argsort keeps static shapes:
    M = B*S*top_k rows always), experts execute as one grouped matmul per
    projection (ops/pallas/grouped_matmul.py), and outputs scatter-add
    back weighted by the gate. Exactly top_k expert-FFNs per token —
    no capacity padding, no token dropping, flops independent of routing
    imbalance.

    On a mesh with dp/fsdp/ep batch sharding the dispatch runs inside a
    shard_map over those axes (a Pallas call can't be GSPMD-partitioned):
    each shard sorts and executes its local tokens against the whole
    expert stack (gathered per shard — the ZeRO-3 fetch semantic), and
    routing statistics average across shards so the aux losses equal the
    global-batch formulas exactly.
    """
    from functools import partial

    from deepspeed_tpu.parallel import topology as topo

    mesh = topo._GLOBAL_MESH
    batch_axes = tuple(
        a for a in ("dp", "fsdp", "ep")
        if mesh is not None and mesh.shape.get(a, 1) > 1)
    if not batch_axes:
        out, stats = _dropless_core(x, router_w, expert_params, cfg,
                                    activation)
        out = constrain_activation(out, ("batch", "seq", "embed"))
        return out, _aux_from_stats(stats, cfg)

    from jax.sharding import PartitionSpec as P

    def local_fn(x, router_w, experts):
        out, stats = _dropless_core(x, router_w, experts, cfg, activation)
        return out, jax.tree.map(lambda s: s[None], stats)  # lead shard dim

    x_spec = P(batch_axes, None, None)
    stat_spec = {k: P(batch_axes)
                 for k in ("me", "ce", "zsq", "expert_load")}
    out, stats_sh = jax.shard_map(
        local_fn, mesh=mesh,
        in_specs=(x_spec, P(), P()),
        out_specs=(x_spec, stat_spec), check_vma=False,
    )(x, router_w, expert_params)
    stats = jax.tree.map(lambda s: jnp.mean(s, axis=0), stats_sh)
    out = constrain_activation(out, ("batch", "seq", "embed"))
    return out, _aux_from_stats(stats, cfg)
