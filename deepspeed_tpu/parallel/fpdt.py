"""FPDT-style chunked attention + host activation offload for multi-M-token
sequences.

Reference: sequence/fpdt_layer.py — ``_FPDTGPUOffloadingAttentionImpl_``
(:545) processes the sequence in chunks, double-buffering chunk
activations through pinned host memory, and chunked FFN/logits (:1126,
:1207) cap the rest of the activation footprint; 16x longer sequences at
~55% MFU (blogs/ulysses-offload).

TPU-native decomposition of the same capability:

  * ``chunked_attention`` — a ``lax.scan`` over Q chunks, each chunk
    scanning KV tiles with exact online-softmax accumulation and
    ``jax.checkpoint`` around the chunk: peak attention memory is one
    [chunk × kv_tile] score block instead of [S × S]. XLA pipelines the
    loops; no custom kernel needed (the Pallas flash kernel covers the
    unchunked case).
  * host offload — instead of FPDT's hand-rolled pinned-buffer double
    buffering, the remat policy ``offload_dots_host``
    (models/transformer.py _REMAT_POLICIES) uses XLA memory kinds
    (device → pinned_host) to spill checkpointed activations to host RAM
    and stream them back in backward, overlapped by XLA's latency-hiding
    scheduler.

Composes with Ulysses/ring: those shard S across chips; this bounds the
per-chip footprint of the resident S/p slice.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _chunk_vs_kv_tiles(q, k_tiles, v_tiles, q_pos0, causal: bool,
                       s_kv: int):
    """One Q chunk against all KV tiles with online softmax (shared
    numerics in parallel/_blockwise.py).

    q: [B,C,N,D]; k_tiles/v_tiles: [T,B,kv_tile,N,D]; q_pos0: global
    position of the chunk's first query; s_kv: real (unpadded) KV length.
    """
    from deepspeed_tpu.parallel._blockwise import (
        block_attn_partial, finalize, init_accumulators, online_merge)

    B, C, N, D = q.shape
    q_pos = q_pos0 + jnp.arange(C)
    kv_tile = k_tiles.shape[2]
    T = k_tiles.shape[0]
    o, m, l = init_accumulators(B, N, C, D)

    # remat the per-tile block: without this the INNER scan's backward
    # saves every tile's [C, kv_tile] softmax block as a residual —
    # stacked to [T, B, N, C, kv_tile] fp32, which is exactly the O(S^2)
    # memory this path exists to avoid (observed: 8GB temp at 128K)
    ck_block = jax.checkpoint(
        lambda q_, k_, v_, qp, kp: block_attn_partial(
            q_, k_, v_, qp, kp, causal, s_kv))

    def body(carry, xs):
        o, m, l = carry
        k_t, v_t, t_idx = xs
        k_pos = t_idx * kv_tile + jnp.arange(kv_tile)
        blk = ck_block(q, k_t, v_t, q_pos, k_pos)
        return online_merge(o, m, l, blk), None

    (o, m, l), _ = lax.scan(body, (o, m, l),
                            (k_tiles, v_tiles, jnp.arange(T)))
    return finalize(o, l, q.dtype)


def chunked_attention(q, k, v, causal: bool = True, q_chunks: int = 4,
                      kv_tile: Optional[int] = None):
    """Exact attention with O(chunk × kv_tile) score memory.

    q,k,v: [B, S, N, D] (equal q/kv head counts — the head-split chunking
    needs them; callers repeat GQA KV first. Same contract as
    ops/attention.py multi_head_attention). ``q_chunks``: number of query
    chunks scanned sequentially, each rematted. ``kv_tile``: KV tile
    length (default S/q_chunks rounded up).
    """
    B, S, N, D = q.shape
    if q_chunks <= 1:
        from deepspeed_tpu.ops.attention import multi_head_attention

        return multi_head_attention(q, k, v, causal=causal)

    pad_q = (-S) % q_chunks
    Sp = S + pad_q
    kv_tile = kv_tile or max(Sp // q_chunks, 1)
    pad_kv = (-S) % kv_tile
    Skv = S + pad_kv

    if pad_q:
        q = jnp.pad(q, [(0, 0), (0, pad_q), (0, 0), (0, 0)])
    if pad_kv:
        k = jnp.pad(k, [(0, 0), (0, pad_kv), (0, 0), (0, 0)])
        v = jnp.pad(v, [(0, 0), (0, pad_kv), (0, 0), (0, 0)])

    C = Sp // q_chunks
    T = Skv // kv_tile
    q_t = jnp.moveaxis(q.reshape(B, q_chunks, C, N, D), 1, 0)
    k_t = jnp.moveaxis(k.reshape(B, T, kv_tile, N, D), 1, 0)
    v_t = jnp.moveaxis(v.reshape(B, T, kv_tile, N, D), 1, 0)

    def chunk_body(_, xs):
        q_c, q_pos0 = xs

        def run(q_c, k_t, v_t, q_pos0):
            return _chunk_vs_kv_tiles(q_c, k_t, v_t, q_pos0, causal, S)

        return None, jax.checkpoint(run)(q_c, k_t, v_t, q_pos0)

    q_pos0s = jnp.arange(q_chunks) * C
    _, out = lax.scan(chunk_body, None, (q_t, q_pos0s))
    out = jnp.moveaxis(out, 0, 1).reshape(B, Sp, N, D)
    return out[:, :S] if pad_q else out
