"""FPDT-style chunked attention + host activation offload for multi-M-token
sequences.

Reference: sequence/fpdt_layer.py — ``_FPDTGPUOffloadingAttentionImpl_``
(:545) processes the sequence in chunks, double-buffering chunk
activations through pinned host memory, and chunked FFN/logits (:1126,
:1207) cap the rest of the activation footprint; 16x longer sequences at
~55% MFU (blogs/ulysses-offload).

TPU-native decomposition of the same capability:

  * ``chunked_attention`` — a ``lax.scan`` over Q chunks, each chunk
    scanning KV tiles with exact online-softmax accumulation and
    ``jax.checkpoint`` around the chunk: peak attention memory is one
    [chunk × kv_tile] score block instead of [S × S]. XLA pipelines the
    loops; no custom kernel needed (the Pallas flash kernel covers the
    unchunked case).
  * host offload — instead of FPDT's hand-rolled pinned-buffer double
    buffering, the remat policy ``offload_dots_host``
    (models/transformer.py _REMAT_POLICIES) uses XLA memory kinds
    (device → pinned_host) to spill checkpointed activations to host RAM
    and stream them back in backward, overlapped by XLA's latency-hiding
    scheduler.

Composes with Ulysses/ring: those shard S across chips; this bounds the
per-chip footprint of the resident S/p slice.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _read_bisect() -> str:
    """DSTPU_FPDT_BISECT debug modes (noctx/outonly/novjp/devout/
    dummybwd) amputate parts of the hosted-layer computation to bisect
    TPU host-offloading failures — gradients (and for some modes the
    outputs) are WRONG. Shout once and count, so a bisect var leaking
    into a real run cannot pass silently."""
    mode = os.environ.get("DSTPU_FPDT_BISECT", "")
    if mode:
        from deepspeed_tpu.utils import telemetry
        from deepspeed_tpu.utils.logging import logger

        telemetry.count("fpdt.bisect_active", mode)
        if ("fpdt.bisect", mode) not in _BISECT_WARNED:
            _BISECT_WARNED.add(("fpdt.bisect", mode))
            logger.warning(
                f"DSTPU_FPDT_BISECT={mode!r} is ACTIVE: this is a debug "
                "bisection mode — fpdt numerics/gradients are "
                "intentionally wrong. Unset it for real runs.")
    return mode


_BISECT_WARNED: set = set()


def _chunk_vs_kv_tiles(q, k_tiles, v_tiles, q_pos0, causal: bool,
                       s_kv: int):
    """One Q chunk against all KV tiles with online softmax (shared
    numerics in parallel/_blockwise.py).

    q: [B,C,N,D]; k_tiles/v_tiles: [T,B,kv_tile,N,D]; q_pos0: global
    position of the chunk's first query; s_kv: real (unpadded) KV length.
    """
    from deepspeed_tpu.parallel._blockwise import (
        block_attn_partial, finalize, init_accumulators, online_merge)

    B, C, N, D = q.shape
    q_pos = q_pos0 + jnp.arange(C)
    kv_tile = k_tiles.shape[2]
    T = k_tiles.shape[0]
    o, m, l = init_accumulators(B, N, C, D)

    # remat the per-tile block: without this the INNER scan's backward
    # saves every tile's [C, kv_tile] softmax block as a residual —
    # stacked to [T, B, N, C, kv_tile] fp32, which is exactly the O(S^2)
    # memory this path exists to avoid (observed: 8GB temp at 128K)
    ck_block = jax.checkpoint(
        lambda q_, k_, v_, qp, kp: block_attn_partial(
            q_, k_, v_, qp, kp, causal, s_kv))

    def body(carry, xs):
        o, m, l = carry
        k_t, v_t, t_idx = xs
        k_pos = t_idx * kv_tile + jnp.arange(kv_tile)
        blk = ck_block(q, k_t, v_t, q_pos, k_pos)
        return online_merge(o, m, l, blk), None

    (o, m, l), _ = lax.scan(body, (o, m, l),
                            (k_tiles, v_tiles, jnp.arange(T)))
    return finalize(o, l, q.dtype)


def chunked_attention(q, k, v, causal: bool = True, q_chunks: int = 4,
                      kv_tile: Optional[int] = None):
    """Exact attention with O(chunk × kv_tile) score memory.

    q,k,v: [B, S, N, D] (equal q/kv head counts — the head-split chunking
    needs them; callers repeat GQA KV first. Same contract as
    ops/attention.py multi_head_attention). ``q_chunks``: number of query
    chunks scanned sequentially, each rematted. ``kv_tile``: KV tile
    length (default S/q_chunks rounded up).
    """
    B, S, N, D = q.shape
    if q_chunks <= 1:
        from deepspeed_tpu.ops.attention import multi_head_attention

        return multi_head_attention(q, k, v, causal=causal)

    pad_q = (-S) % q_chunks
    Sp = S + pad_q
    kv_tile = kv_tile or max(Sp // q_chunks, 1)
    pad_kv = (-S) % kv_tile
    Skv = S + pad_kv

    if pad_q:
        q = jnp.pad(q, [(0, 0), (0, pad_q), (0, 0), (0, 0)])
    if pad_kv:
        k = jnp.pad(k, [(0, 0), (0, pad_kv), (0, 0), (0, 0)])
        v = jnp.pad(v, [(0, 0), (0, pad_kv), (0, 0), (0, 0)])

    C = Sp // q_chunks
    T = Skv // kv_tile
    q_t = jnp.moveaxis(q.reshape(B, q_chunks, C, N, D), 1, 0)
    k_t = jnp.moveaxis(k.reshape(B, T, kv_tile, N, D), 1, 0)
    v_t = jnp.moveaxis(v.reshape(B, T, kv_tile, N, D), 1, 0)

    def chunk_body(_, xs):
        q_c, q_pos0 = xs

        def run(q_c, k_t, v_t, q_pos0):
            return _chunk_vs_kv_tiles(q_c, k_t, v_t, q_pos0, causal, S)

        return None, jax.checkpoint(run)(q_c, k_t, v_t, q_pos0)

    q_pos0s = jnp.arange(q_chunks) * C
    _, out = lax.scan(chunk_body, None, (q_t, q_pos0s))
    out = jnp.moveaxis(out, 0, 1).reshape(B, Sp, N, D)
    return out[:, :S] if pad_q else out


# ---------------------------------------------------------------------------
# host-KV streaming attention block (beyond-HBM sequence lengths)
# ---------------------------------------------------------------------------


def _to_host(x):
    """Move to pinned host memory inside jit (no-op placement on CPU)."""
    from deepspeed_tpu.utils import memspace

    return memspace.put(x, "pinned_host")


def _to_device(x):
    from deepspeed_tpu.utils import memspace

    return memspace.put(x, "device")


def _fetch_tile(stacked, t_idx):
    """Stream one [B, kv_tile, Nkv, D] tile of a host-resident stack to
    the device."""
    return _to_device(lax.dynamic_index_in_dim(stacked, t_idx,
                                               keepdims=False))


def _masked_scores(q_c, k_rep, q_pos, k_pos, causal: bool, s_valid: int):
    """Scaled masked scores [B, N, C, kv_tile] — must match the forward
    numerics exactly (same einsum + mask as _blockwise)."""
    d = q_c.shape[-1]
    s = jnp.einsum("bqnd,bknd->bnqk", q_c, k_rep).astype(jnp.float32)
    s = s / jnp.sqrt(jnp.asarray(d, jnp.float32))
    mask = k_pos[None, :] < s_valid
    if causal:
        mask = mask & (k_pos[None, :] <= q_pos[:, None])
    else:
        mask = jnp.broadcast_to(mask, (q_pos.shape[0], k_pos.shape[0]))
    return jnp.where(mask[None, None, :, :], s, -jnp.inf)


def _repeat_tile(tile, g: int):
    return jnp.repeat(tile, g, axis=2) if g > 1 else tile


def _unrepeat_grad(grad_rep, g: int):
    """[B, kv_tile, Nkv*g, D] cotangent → summed back to kv heads."""
    if g == 1:
        return grad_rep
    B, T, NG, D = grad_rep.shape
    return grad_rep.reshape(B, T, NG // g, g, D).sum(axis=3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _stream_attn(q_c, k_t, v_t, q_pos, n_tiles, g, s_valid, causal,
                 kv_tile):
    """One q-chunk against host-resident KV tiles, flash-style exact
    softmax. The custom VJP recomputes per-tile probabilities from the
    saved logsumexp instead of differentiating through the online-merge
    scan — without it the scan's backward stacks every tile's fp32
    (o, m, l) carry, an O(S * N * D) residual that is exactly the memory
    this path exists to avoid (observed: 2x8GB at 512K)."""
    ctx, _ = _stream_attn_fwd_impl(q_c, k_t, v_t, q_pos, n_tiles, g,
                                   s_valid, causal, kv_tile)
    return ctx


def _stream_attn_fwd_impl(q_c, k_t, v_t, q_pos, n_tiles, g, s_valid,
                          causal, kv_tile):
    B, C, N, D = q_c.shape
    T = k_t.shape[0]

    def _untile(flat):
        # host stacks are [T, B*kv_tile*Nkv*D] (2-D dodges an XLA
        # async-copy layout bug on 5-D host moves)
        return flat.reshape(B, kv_tile, N // g, D)

    o = jnp.zeros((B, N, C, D), jnp.float32)
    m = jnp.full((B, N, C), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, N, C), jnp.float32)

    def tile_body(carry, t_idx):
        o, m, l = carry
        k_rep = _repeat_tile(_untile(_fetch_tile(k_t, t_idx)), g)
        v_rep = _repeat_tile(_untile(_fetch_tile(v_t, t_idx)), g)
        k_pos = t_idx * kv_tile + jnp.arange(kv_tile)
        s = _masked_scores(q_c, k_rep, q_pos, k_pos, causal, s_valid)
        m_blk = jnp.max(s, axis=-1)
        valid = jnp.isfinite(m_blk)
        m_safe = jnp.where(valid, m_blk, 0.0)
        p = jnp.exp(s - m_safe[..., None])
        l_blk = jnp.where(valid, jnp.sum(p, axis=-1), 0.0)
        o_blk = jnp.einsum("bnqk,bknd->bnqd", p,
                           v_rep.astype(jnp.float32))
        m_new = jnp.maximum(m, jnp.where(valid, m_blk, -jnp.inf))
        m_new_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new_safe), 0.0)
        beta = jnp.where(valid, jnp.exp(m_blk - m_new_safe), 0.0)
        o = o * alpha[..., None] + o_blk * beta[..., None]
        l = l * alpha + l_blk * beta
        return (o, m_new, l), None

    def guarded(carry, t_idx):
        return lax.cond(t_idx < n_tiles,
                        lambda c: tile_body(c, t_idx)[0],
                        lambda c: c, carry), None

    (o, m, l), _ = lax.scan(guarded, (o, m, l), jnp.arange(T))
    l_safe = jnp.maximum(l, 1e-30)
    ctx = jnp.transpose(o / l_safe[..., None], (0, 2, 1, 3)) \
        .astype(q_c.dtype)                                   # [B,C,N,D]
    lse = jnp.where(l > 0, jnp.where(jnp.isfinite(m), m, 0.0)
                    + jnp.log(l_safe), 0.0)                  # [B,N,C]
    return ctx, lse


def _stream_attn_fwd(q_c, k_t, v_t, q_pos, n_tiles, g, s_valid, causal,
                     kv_tile):
    ctx, lse = _stream_attn_fwd_impl(q_c, k_t, v_t, q_pos, n_tiles, g,
                                     s_valid, causal, kv_tile)
    return ctx, (q_c, k_t, v_t, q_pos, n_tiles, ctx, lse)


def _stream_attn_bwd(g, s_valid, causal, kv_tile, res, dctx):
    import numpy as np

    q_c, k_t, v_t, q_pos, n_tiles, ctx, lse = res
    B, C, N, D = q_c.shape
    T = k_t.shape[0]

    def _untile(flat):
        return flat.reshape(B, kv_tile, N // g, D)

    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    dctx32 = jnp.transpose(dctx.astype(jnp.float32), (0, 2, 1, 3))
    ctx32 = jnp.transpose(ctx.astype(jnp.float32), (0, 2, 1, 3))
    delta = jnp.sum(dctx32 * ctx32, axis=-1)                 # [B,N,C]

    dq = jnp.zeros((B, N, C, D), jnp.float32)
    dk_t = jnp.zeros_like(k_t)
    dv_t = jnp.zeros_like(v_t)

    def tile_body(carry, t_idx):
        dq, dk_t, dv_t = carry
        k_tile = _untile(_fetch_tile(k_t, t_idx))
        v_tile = _untile(_fetch_tile(v_t, t_idx))
        k_rep = _repeat_tile(k_tile, g)
        v_rep = _repeat_tile(v_tile, g)
        k_pos = t_idx * kv_tile + jnp.arange(kv_tile)
        s = _masked_scores(q_c, k_rep, q_pos, k_pos, causal, s_valid)
        p = jnp.exp(s - lse[..., None])                      # [B,N,C,kt]
        # dv[k] = sum_q p * dctx ; dp = dctx . v ; ds = p (dp - delta)
        dv_rep = jnp.einsum("bnqk,bnqd->bknd", p, dctx32)
        dp = jnp.einsum("bnqd,bknd->bnqk", dctx32,
                        v_rep.astype(jnp.float32))
        ds = p * (dp - delta[..., None])
        dq = dq + jnp.einsum("bnqk,bknd->bnqd", ds,
                             k_rep.astype(jnp.float32)) * scale
        dk_rep = jnp.einsum("bnqk,bnqd->bknd", ds,
                            q_c.astype(jnp.float32).transpose(0, 2, 1, 3)
                            ) * scale
        dk_tile = _unrepeat_grad(dk_rep, g).astype(k_t.dtype)
        dv_tile = _unrepeat_grad(dv_rep, g).astype(v_t.dtype)
        dk_t2 = lax.dynamic_update_index_in_dim(
            dk_t, dk_tile.reshape(dk_t.shape[1:]), t_idx, 0)
        dv_t2 = lax.dynamic_update_index_in_dim(
            dv_t, dv_tile.reshape(dv_t.shape[1:]), t_idx, 0)
        return (dq, dk_t2, dv_t2), None

    def guarded(carry, t_idx):
        return lax.cond(t_idx < n_tiles,
                        lambda c: tile_body(c, t_idx)[0],
                        lambda c: c, carry), None

    (dq, dk_t, dv_t), _ = lax.scan(guarded, (dq, dk_t, dv_t),
                                   jnp.arange(T))
    dq_out = jnp.transpose(dq, (0, 2, 1, 3)).astype(q_c.dtype)
    zero_pos = np.zeros(q_pos.shape, dtype=jax.dtypes.float0)
    zero_nt = np.zeros((), dtype=jax.dtypes.float0)
    return dq_out, dk_t, dv_t, zero_pos, zero_nt


_stream_attn.defvjp(_stream_attn_fwd, _stream_attn_bwd)


def fpdt_attention_block(y, ap, positions, *, num_heads: int,
                         kv_heads: int, head_dim: int,
                         rope_theta: Optional[float], q_chunks: int,
                         kv_tile: Optional[int] = None, causal: bool = True,
                         use_biases: bool = False,
                         norm_fn: Optional[callable] = None,
                         post_fn: Optional[callable] = None,
                         hosted: bool = False,
                         seq_len: Optional[int] = None,
                         sp_axis: Optional[str] = None,
                         sp_size: int = 1) -> jax.Array:
    """Full FPDT attention sub-layer with host-resident KV streaming —
    the reference ``_FPDTGPUOffloadingAttentionImpl_``'s pinned
    double-buffered sequence chunks (sequence/fpdt_layer.py:545,
    ``SequenceChunk`` :497) as XLA memory-space movement.

    y: [B, S, H] layer input (device) — pre-norm when ``norm_fn`` is
    given (the norm then applies per chunk inside the scans, so neither
    the normed full-S activation nor its fp32 intermediate ever
    materializes — the reference chunks the whole layer pass the same
    way, fpdt_layer.py:1126). Returns the attention branch output
    [B, S, H] (wo applied). Device never holds a full-S [B, S, Nq, D]
    query/output tensor or repeated-KV tensor:

      * K/V build scans sequence tiles: per tile (norm→) project at
        kv_heads width (the GQA-narrow 1/g footprint), rotate, and
        write into pinned-host stacks;
      * the q-chunk scan projects each chunk's queries on the fly and
        streams KV tiles back one at a time, accumulating each chunk's
        wo-contracted output into a carried [B, Sp, H] buffer (scan
        in-places the carry — no stacked-ys + reshape double buffer);
      * the backward replays chunk bodies (remat), re-streaming tiles
        from host, so residuals are O(B*S*H) rather than O(B*S*Nq*D).

    ``hosted=True`` is the residual-stream-offload mode (VERDICT r4 #5,
    reference fpdt_layer.py:545's SequenceChunk applied to the residual
    itself): ``y`` is a HOST stack [q_chunks, B*C, H] (the padded
    sequence pre-split on the chunk grid), ``seq_len`` gives the real S,
    and the return value is the same-shaped host stack of layer outputs
    — the device never holds any full-S [B, S, H] buffer, only one
    chunk (+ one KV-build tile) at a time. The KV tile grid is forced
    onto the chunk grid so both scans fetch the same host tiles.

    ``sp_axis`` is the sequence-parallel composition mode: the call runs
    INSIDE ``shard_map`` over that mesh axis with ``y``/``positions``
    holding this rank's LOCAL [B, S/p, ...] shard (rank r owns the
    contiguous global span [r·S/p, (r+1)·S/p)). Each rank builds its
    local KV tile stacks, all-gathers them over ``sp_axis`` (rank-major
    tiled gather ⇒ the gathered tile order is position-sorted, so tile j
    still starts at global position j·kv_tile), spills the GLOBAL stacks
    to host, and streams them through its local q chunks with
    shard-offset query positions. ``sp_size`` must be the static degree
    of ``sp_axis`` (the global valid length S·p is a nondiff argument of
    the streaming kernel, so it cannot be derived from a traced
    ``axis_size`` on older jax).
    """
    if sp_axis is not None and hosted:
        raise ValueError("fpdt sp composition does not support the "
                         "hosted-residual mode (fpdt_host_residual)")
    if hosted:
        if seq_len is None:
            raise ValueError(
                "hosted fpdt requires seq_len (the host stack is padded "
                "on the chunk grid, so the real sequence length cannot "
                "be recovered from y.shape)")
        T_res, BC, H = y.shape
        if q_chunks != T_res:
            raise ValueError(
                f"hosted fpdt: q_chunks={q_chunks} must equal the host "
                f"stack's chunk count {T_res}")
        S = seq_len
        C = -(-S // q_chunks)  # ceil
        # the stack is padded on the chunk grid by construction
        Sp = q_chunks * C
        assert BC % C == 0, (BC, C)
        B = BC // C
        if kv_tile not in (None, C):
            raise ValueError("hosted fpdt uses the chunk grid for KV "
                             f"tiles; got kv_tile={kv_tile} != C={C}")
        kv_tile = C
        T = q_chunks
        dt = y.dtype
        g = num_heads // kv_heads
        positions = jnp.broadcast_to(positions, (B, S))
        pos_p = (jnp.pad(positions, [(0, 0), (0, Sp - S)]) if Sp > S
                 else positions)

        def _res_tile(t):
            """Fetch residual chunk t from the host stack → [B, C, H]."""
            return _to_device(lax.dynamic_index_in_dim(
                y, t, keepdims=False)).reshape(B, C, H)
    elif sp_axis is not None:
        # sp composition (runs inside shard_map): y/positions are the
        # LOCAL shard. Padding a local shard would insert pad rows
        # mid-sequence GLOBALLY and break the position math, so the
        # chunk/tile grids must divide the shard exactly — the planner
        # (parallel/auto_sp.py) only ever picks divisible counts.
        B, S, H = y.shape
        dt = y.dtype
        g = num_heads // kv_heads
        positions = jnp.broadcast_to(positions, (B, S))
        if S % q_chunks:
            raise ValueError(
                f"fpdt+sp: local sequence shard {S} must be divisible "
                f"by q_chunks={q_chunks} (pad-free composition only)")
        pad_q = 0
        Sp = S
        C = S // q_chunks
        kv_tile = kv_tile or C
        if S % kv_tile:
            raise ValueError(
                f"fpdt+sp: local sequence shard {S} must be divisible "
                f"by kv_tile={kv_tile} (pad-free composition only)")
        T_loc = S // kv_tile               # tiles this rank builds
        T = sp_size * T_loc                # global tile count streamed
        y_p, pos_p = y, positions

        def _res_tile(t):
            return lax.dynamic_slice_in_dim(y_p, t * kv_tile, kv_tile, 1)
    else:
        B, S, H = y.shape
        dt = y.dtype
        g = num_heads // kv_heads
        positions = jnp.broadcast_to(positions, (B, S))

        pad_q = (-S) % q_chunks
        Sp = S + pad_q
        C = Sp // q_chunks
        kv_tile = kv_tile or C
        pad_kv = (-S) % kv_tile
        Skv = S + pad_kv
        T = Skv // kv_tile

        # one padded view serves both the q chunks and the kv tiles
        P = max(Sp, Skv)
        y_p = jnp.pad(y, [(0, 0), (0, P - S), (0, 0)]) if P > S else y
        pos_p = (jnp.pad(positions, [(0, 0), (0, P - S)]) if P > S
                 else positions)

        def _res_tile(t):
            return lax.dynamic_slice_in_dim(y_p, t * kv_tile, kv_tile, 1)

    # sp composition globals: this rank's queries live at global
    # positions shard_off + [0, S); KV/softmax masking runs against the
    # GLOBAL valid length (static — _stream_attn nondiff arg)
    if sp_axis is not None:
        shard_off = lax.axis_index(sp_axis) * S
        s_valid = sp_size * S
    else:
        shard_off = 0
        s_valid = S

    def maybe_norm(t):
        return norm_fn(t) if norm_fn is not None else t

    def proj_tile(yt, w, b):
        out = jnp.einsum("bch,hnd->bcnd", yt, w.astype(dt))
        if use_biases:
            out = out + b.astype(dt)
        return out

    # K/V build: scan tiles — per tile (norm→) project+rotate — stacking
    # on device at kv_heads width (1/g of the repeated footprint; ~2GB
    # at 512K vs 4.3GB for one full-S hidden), then one move to host.
    # Pad tiles carry norm-of-zero garbage; _masked_scores' k_pos <
    # s_valid mask keeps them out of every softmax. (Stacks can't build
    # directly into host buffers: autodiff of a host-carried
    # dynamic_update scan makes mixed-memory-space cotangents.)
    def kv_tile_fn(t):
        x_tile = _res_tile(t)
        p_tile = lax.dynamic_slice_in_dim(pos_p, t * kv_tile, kv_tile, 1)
        yt = maybe_norm(x_tile)
        kt = proj_tile(yt, ap["wk"], ap.get("bk"))
        vt = proj_tile(yt, ap["wv"], ap.get("bv"))
        if rope_theta:
            kt = _rope_chunk(kt, p_tile, rope_theta)
        # [rows, head_dim] keeps the lane dim: fully flat 1-D tiles trip
        # the TPU async dynamic-index emitter's sublane alignment CHECK
        return (kt.reshape(-1, head_dim), vt.reshape(-1, head_dim))

    # remat per tile: without it the scan's backward saves every tile's
    # norm fp32 intermediates — stacked [T, ...] f32, exactly the full-S
    # footprint this path removes. The host move stays OUTSIDE the
    # rematted region (its replay would mix memory spaces), and happens
    # per flattened tile: the stacked host result is [T, tile_elems]
    # built from 1-D per-step copies (bulk D2H of a multi-dim stack
    # trips an XLA async-copy layout-assignment mismatch on TPU);
    # _stream_attn re-shapes per fetched tile.
    kv_tile_fn = jax.checkpoint(kv_tile_fn)

    if sp_axis is not None:
        # build the LOCAL tile stacks on device, all-gather them over
        # the sp axis, then spill the GLOBAL stacks to host. The tiled
        # gather concatenates in axis-index (= rank) order and rank r's
        # tokens occupy the contiguous global span [r·S, (r+1)·S), so
        # the gathered stack is position-sorted: _stream_attn's internal
        # k_pos = t·kv_tile + arange(kv_tile) stays valid unchanged.
        # The gather's AD transpose is a reduce-scatter, which routes
        # each rank's dk/dv tile cotangents back to the owning rank.
        from deepspeed_tpu.comm import comm as _comm

        def kv_body(_, t):
            return None, kv_tile_fn(t)

        _, (k_loc, v_loc) = lax.scan(kv_body, None, jnp.arange(T_loc))
        k_t = _to_host(_comm.all_gather(k_loc, sp_axis, gather_dim=0,
                                        log_name="fpdt_sp_kv"))
        v_t = _to_host(_comm.all_gather(v_loc, sp_axis, gather_dim=0,
                                        log_name="fpdt_sp_kv"))
    else:
        def kv_body(_, t):
            kt, vt = kv_tile_fn(t)
            return None, (_to_host(kt), _to_host(vt))

        _, (k_t, v_t) = lax.scan(kv_body, None, jnp.arange(T))

    wo = ap["wo"].astype(dt)

    def chunk(x_chunk, pos_chunk, chunk_idx):
        y_chunk = maybe_norm(x_chunk)
        q_c = jnp.einsum("bch,hnd->bcnd", y_chunk, ap["wq"].astype(dt))
        if use_biases:
            q_c = q_c + ap["bq"].astype(dt)
        if rope_theta:
            q_c = _rope_chunk(q_c, pos_chunk, rope_theta)
        q_pos = shard_off + chunk_idx * C + jnp.arange(C)

        # causal: later tiles are fully masked for this chunk — skipped
        # entirely inside _stream_attn (no H2D fetch, no compute).
        # shard_off shifts the cutoff to this rank's global span in the
        # sp composition (0 otherwise).
        n_tiles = (jnp.minimum(
            (shard_off + (chunk_idx + 1) * C + kv_tile - 1) // kv_tile, T)
            if causal else jnp.asarray(T, jnp.int32))

        ctx = _stream_attn(q_c, k_t, v_t, q_pos, n_tiles, g, s_valid,
                           causal, kv_tile)
        attn_c = jnp.einsum("bcnd,ndh->bch", ctx, wo)
        if post_fn is not None:
            # fuse the rest of the transformer block into the same
            # chunk (residual add + ln2 + MLP — all position-wise): the
            # layer emits ONE full-S buffer instead of separate
            # attention-out and MLP-out full-S intermediates (reference
            # chunks the whole layer pass, fpdt_layer.py:1126)
            return post_fn(x_chunk, attn_c)
        return attn_c

    if hosted:
        # emit each chunk's result straight back to the host stack (scan
        # ys — the same pattern as the KV build; a host CARRY with
        # dynamic_update makes mixed-memory-space cotangents). The FETCH
        # stays INSIDE the rematted region: the saved residual is then
        # the (loop-invariant) host stack itself, not a per-chunk device
        # copy — stacked fetched chunks would rebuild the full-S device
        # buffer this mode exists to remove. The host EMISSION stays
        # outside (a replayed D2H would mix memory spaces).
        def hosted_chunk(idx):
            x_chunk = _res_tile(idx)
            p_chunk = lax.dynamic_slice_in_dim(pos_p, idx * C, C, axis=1)
            return chunk(x_chunk, p_chunk, idx)

        hosted_chunk = jax.checkpoint(hosted_chunk)

        def hosted_body(_, idx):
            return None, _to_host(hosted_chunk(idx).reshape(B * C, H))

        _, out_t = lax.scan(hosted_body, None, jnp.arange(q_chunks))
        return out_t

    def chunk_body(buf, idx):
        # slice the chunk in-body (a pre-split [q_chunks, B, C, H] copy
        # would be a second full-sequence buffer) and write the result
        # into the carried output buffer (scan in-places the carry — a
        # stacked-ys + moveaxis/reshape epilogue would transiently hold
        # two full-sequence copies)
        x_chunk = lax.dynamic_slice_in_dim(y_p, idx * C, C, axis=1)
        p_chunk = lax.dynamic_slice_in_dim(pos_p, idx * C, C, axis=1)
        res = jax.checkpoint(chunk)(x_chunk, p_chunk, idx)
        return lax.dynamic_update_slice_in_dim(buf, res, idx * C, 1), None

    out, _ = lax.scan(chunk_body, jnp.zeros((B, Sp, H), dt),
                      jnp.arange(q_chunks))
    return out[:, :S] if pad_q else out


def _rope_chunk(x, positions, theta: float):
    from deepspeed_tpu.models.transformer import _rope

    return _rope(x, positions, theta)


# ---------------------------------------------------------------------------
# hosted-residual fused layer with a two-pass flash-style backward
# ---------------------------------------------------------------------------


def fpdt_hosted_layer(x_t, layer_params, pos_p, *, seq_len: int,
                      q_chunks: int, num_heads: int, kv_heads: int,
                      head_dim: int, rope_theta, use_biases: bool,
                      norm_kind: str, norm_eps: float, activation: str):
    """One fused transformer block over a HOST residual chunk stack, with
    a layer-level custom VJP whose backward runs in TWO passes (the
    flash-attention backward split, applied at the host-streaming level):

      pass A (chunk-outer): per q-chunk — tail (wo/residual/ln2/MLP) vjp,
        the dq tile loop, and the q-projection/ln1 vjp; emits the partial
        d(x) chunk plus (q, d_ctx, delta) stacks for pass B.
      pass B (tile-outer): per KV tile — accumulates dk/dv from all
        later chunks (recomputing probabilities from the saved lse), then
        the KV-build vjp; adds the kv-path d(x) into pass A's partial.

    Why not plain autodiff of the chunk scan (the r4 structure): each
    chunk's KV cotangent is a full [T, ...] stack, and the scan transpose
    accumulates those across chunks — an O(S)-sized host add per chunk
    (~800 GB of hidden traffic at 512K) whose operands XLA stages
    through HBM; that accumulation is exactly what made 512K OOM at
    21.8 GB temp. Here every host object is written once and read O(1)
    or O(T) times with tile-sized buffers only.

    x_t: [q_chunks, B*C, H] host stack; pos_p: [B, Sp] int32 (device).
    Returns the same-shaped host stack. Reference:
    sequence/fpdt_layer.py:545 (chunked layer + offload), backward split
    per the standard flash-attention dq/dkv loop exchange.
    """
    import math

    from deepspeed_tpu.models.transformer import _norm, act_fn

    T, BC, H = x_t.shape
    S = seq_len
    C = -(-S // q_chunks)
    Sp = q_chunks * C
    assert T == q_chunks and BC % C == 0
    B = BC // C
    N, D = num_heads, head_dim
    g = num_heads // kv_heads
    dt = x_t.dtype
    scale = 1.0 / math.sqrt(D)

    # -- pure per-chunk pieces (jax.vjp'd in the backward) ---------------
    def head_q(x_c, p_c, params):
        ap = params["attn"]
        y = _norm(x_c, params["ln1"], norm_kind, norm_eps)
        q = jnp.einsum("bch,hnd->bcnd", y, ap["wq"].astype(dt))
        if use_biases:
            q = q + ap["bq"].astype(dt)
        if rope_theta:
            q = _rope_chunk(q, p_c, rope_theta)
        return q

    def build_kv(x_c, p_c, params):
        ap = params["attn"]
        y = _norm(x_c, params["ln1"], norm_kind, norm_eps)
        k = jnp.einsum("bch,hnd->bcnd", y, ap["wk"].astype(dt))
        v = jnp.einsum("bch,hnd->bcnd", y, ap["wv"].astype(dt))
        if use_biases:
            k = k + ap["bk"].astype(dt)
            v = v + ap["bv"].astype(dt)
        if rope_theta:
            k = _rope_chunk(k, p_c, rope_theta)
        return k, v

    def tail(x_c, ctx_c, params):
        ap = params["attn"]
        attn = jnp.einsum("bcnd,ndh->bch", ctx_c, ap["wo"].astype(dt))
        if use_biases:
            attn = attn + ap["bo"].astype(dt)
        xc2 = x_c + attn
        mp = params["mlp"]
        y2 = _norm(xc2, params["ln2"], norm_kind, norm_eps)
        if activation == "swiglu":
            gate = jnp.einsum("bch,hf->bcf", y2, mp["wg"].astype(dt))
            up = jnp.einsum("bch,hf->bcf", y2, mp["wi"].astype(dt))
            z = jax.nn.silu(gate) * up
        else:
            pre = jnp.einsum("bch,hf->bcf", y2, mp["wi"].astype(dt))
            if use_biases:
                pre = pre + mp["bi"].astype(dt)
            z = act_fn(activation)(pre)
        out = jnp.einsum("bcf,fh->bch", z, mp["wo"].astype(dt))
        if use_biases:
            out = out + mp["bo"].astype(dt)
        return xc2 + out

    def fetch_rows(stack, i, shape):
        return _to_device(lax.dynamic_index_in_dim(
            stack, i, keepdims=False)).reshape(shape)

    def pos_chunk(i):
        return lax.dynamic_slice_in_dim(pos_p, i * C, C, axis=1)

    def n_tiles_of(idx):
        return jnp.minimum(idx + 1, T).astype(jnp.int32)

    # -- forward ---------------------------------------------------------
    def _kv_build(x_t, params):
        def f(t):
            x_tile = fetch_rows(x_t, t, (B, C, H))
            k, v = build_kv(x_tile, pos_chunk(t), params)
            return k.reshape(-1, D), v.reshape(-1, D)

        f = jax.checkpoint(f)

        def body(_, t):
            kt, vt = f(t)
            return None, (_to_host(kt), _to_host(vt))

        _, (k_t, v_t) = lax.scan(body, None, jnp.arange(T))
        return k_t, v_t

    def _forward(x_t, params):
        k_t, v_t = _kv_build(x_t, params)

        def f(idx):
            x_c = fetch_rows(x_t, idx, (B, C, H))
            q_c = head_q(x_c, pos_chunk(idx), params)
            q_pos = idx * C + jnp.arange(C)
            ctx, lse = _stream_attn_fwd_impl(
                q_c, k_t, v_t, q_pos, n_tiles_of(idx), g, S, True, C)
            out_c = tail(x_c, ctx, params)
            return out_c, ctx, lse

        f = jax.checkpoint(f)

        _bisect = _read_bisect()

        def body_noctx(_, idx):
            out_c, ctx, lse = f(idx)
            return None, _to_host(out_c.reshape(BC, H))

        def body(_, idx):
            out_c, ctx, lse = f(idx)
            if "outonly" in _bisect:
                return None, (_to_host(out_c.reshape(BC, H)),
                              _to_host(ctx.reshape(B * C * N, D) * 0)[:1],
                              _to_host(lse * 0)[:1])
            # ys must be uniformly host-resident: a mixed host/device ys
            # tuple in one scan trips the TPU host-offloading pass
            # ("moved to host ... layout for this output is not set")
            return None, (_to_host(out_c.reshape(BC, H)),
                          _to_host(ctx.reshape(B * C * N, D)),
                          _to_host(lse))

        if "noctx" in _bisect:
            _, out_t = lax.scan(body_noctx, None, jnp.arange(T))
            return out_t, (k_t, v_t, out_t, out_t)
        _, (out_t, ctx_t, lse_t) = lax.scan(body, None, jnp.arange(T))
        return out_t, (k_t, v_t, ctx_t, lse_t)

    @jax.custom_vjp
    def run(x_t, params, pos_p):
        out_t, _ = _forward(x_t, params)
        return out_t

    def run_fwd(x_t, params, pos_p):
        out_t, (k_t, v_t, ctx_t, lse_t) = _forward(x_t, params)
        return out_t, (x_t, params, k_t, v_t, ctx_t, lse_t)

    def run_bwd(res, d_out_t):
        import numpy as np

        x_t, params, k_t, v_t, ctx_t, lse_t = res
        f32 = jnp.float32
        dparams0 = jax.tree.map(
            lambda p: jnp.zeros(p.shape, f32), params)

        def _untile_kv(flat):
            return flat.reshape(B, C, N // g, D)

        # ---- pass A: chunk-outer — tail vjp, dq, q-path vjp -----------
        def a_step(dparams, idx):
            x_c = fetch_rows(x_t, idx, (B, C, H))
            d_out_c = fetch_rows(d_out_t, idx, (B, C, H))
            ctx_c = fetch_rows(ctx_t, idx, (B, C, N, D))
            lse_c = _to_device(lse_t[idx])                    # [B,N,C]
            p_c = pos_chunk(idx)
            q_c = head_q(x_c, p_c, params)                    # replay
            q_pos = idx * C + jnp.arange(C)

            _, tail_vjp = jax.vjp(
                lambda xx, cc, pp: tail(xx, cc, pp), x_c, ctx_c, params)
            dx_post, d_ctx, dp_tail = tail_vjp(d_out_c)
            d_ctx32 = jnp.transpose(d_ctx.astype(f32), (0, 2, 1, 3))
            ctx32 = jnp.transpose(ctx_c.astype(f32), (0, 2, 1, 3))
            delta = jnp.sum(d_ctx32 * ctx32, axis=-1)         # [B,N,C]

            nt = n_tiles_of(idx)
            dq0 = jnp.zeros((B, N, C, D), f32)

            def dq_tile(dq, t):
                def live(dq):
                    k_rep = _repeat_tile(_untile_kv(_fetch_tile(k_t, t)), g)
                    v_rep = _repeat_tile(_untile_kv(_fetch_tile(v_t, t)), g)
                    k_pos = t * C + jnp.arange(C)
                    s = _masked_scores(q_c, k_rep, q_pos, k_pos, True, S)
                    p = jnp.exp(s - lse_c[..., None])
                    dp = jnp.einsum("bnqd,bknd->bnqk", d_ctx32,
                                    v_rep.astype(f32))
                    ds = p * (dp - delta[..., None])
                    return dq + jnp.einsum(
                        "bnqk,bknd->bnqd", ds, k_rep.astype(f32)) * scale

                return lax.cond(t < nt, live, lambda d: d, dq), None

            dq, _ = lax.scan(dq_tile, dq0, jnp.arange(T))
            dq = jnp.transpose(dq, (0, 2, 1, 3)).astype(q_c.dtype)

            _, q_vjp = jax.vjp(
                lambda xx, pp: head_q(xx, p_c, pp), x_c, params)
            dx_q, dp_q = q_vjp(dq)
            dparams = jax.tree.map(
                lambda a, b, c: a + b.astype(f32) + c.astype(f32),
                dparams, dp_tail, dp_q)
            dx_c = (dx_post + dx_q).astype(dt)
            return dparams, (_to_host(dx_c.reshape(BC, H)),
                             _to_host(q_c.reshape(B * C * N, D)),
                             _to_host(d_ctx.reshape(B * C * N, D)),
                             _to_host(delta))

        dparams, (dxa_t, q_t, dctx_t, delta_t) = lax.scan(
            a_step, dparams0, jnp.arange(T))

        # ---- pass B: tile-outer — dk/dv from all later chunks, kv vjp -
        def b_step(dparams, t):
            x_tile = fetch_rows(x_t, t, (B, C, H))
            p_tile = pos_chunk(t)
            k_rep = _repeat_tile(_untile_kv(_fetch_tile(k_t, t)), g)
            v_rep = _repeat_tile(_untile_kv(_fetch_tile(v_t, t)), g)
            k_pos = t * C + jnp.arange(C)
            dk0 = jnp.zeros((B, C, N, D), f32)  # repeated-head layout
            dv0 = jnp.zeros((B, C, N, D), f32)

            def kv_chunk(carry, c):
                dk, dv = carry

                def live(carry):
                    dk, dv = carry
                    q_c = fetch_rows(q_t, c, (B, C, N, D))
                    d_ctx = fetch_rows(dctx_t, c, (B, C, N, D))
                    d_ctx32 = jnp.transpose(d_ctx.astype(f32),
                                            (0, 2, 1, 3))
                    lse_c = _to_device(lse_t[c])
                    delta_c = _to_device(delta_t[c])
                    q_pos = c * C + jnp.arange(C)
                    s = _masked_scores(q_c, k_rep, q_pos, k_pos, True, S)
                    p = jnp.exp(s - lse_c[..., None])
                    dv2 = dv + jnp.einsum("bnqk,bnqd->bknd", p, d_ctx32)
                    dp = jnp.einsum("bnqd,bknd->bnqk", d_ctx32,
                                    v_rep.astype(f32))
                    ds = p * (dp - delta_c[..., None])
                    dk2 = dk + jnp.einsum(
                        "bnqk,bnqd->bknd", ds,
                        q_c.astype(f32).transpose(0, 2, 1, 3)) * scale
                    return dk2, dv2

                return lax.cond(c >= t, live, lambda cc: cc, (dk, dv)), None

            (dk, dv), _ = lax.scan(kv_chunk, (dk0, dv0), jnp.arange(T))
            dk_tile = _unrepeat_grad(dk, g).astype(dt)
            dv_tile = _unrepeat_grad(dv, g).astype(dt)
            _, kv_vjp = jax.vjp(
                lambda xx, pp: build_kv(xx, p_tile, pp), x_tile, params)
            dx_kv, dp_kv = kv_vjp((dk_tile, dv_tile))
            dparams = jax.tree.map(
                lambda a, b: a + b.astype(f32), dparams, dp_kv)
            dxa = fetch_rows(dxa_t, t, (B, C, H))
            dx_total = (dxa + dx_kv).astype(dt)
            return dparams, _to_host(dx_total.reshape(BC, H))

        dparams, dx_t = lax.scan(b_step, dparams, jnp.arange(T))
        dparams = jax.tree.map(lambda gg, p: gg.astype(p.dtype),
                               dparams, params)
        d_pos = np.zeros(np.shape(pos_p), jax.dtypes.float0)
        return dx_t, dparams, d_pos

    _bis = _read_bisect()
    if "novjp" in _bis:
        return _forward(x_t, layer_params)[0]
    if "devout" in _bis:
        @jax.custom_vjp
        def run_d(x_t, params, pos_p):
            out_t, _ = _forward(x_t, params)
            return _to_device(out_t)

        def run_d_fwd(x_t, params, pos_p):
            out_t, res_extra = _forward(x_t, params)
            return _to_device(out_t), (x_t, params) + res_extra

        def run_d_bwd(res, d_out):
            import numpy as np
            x_t, params, *_ = res
            dx = _to_host(jax.tree.map(
                lambda a: jnp.zeros(a.shape, a.dtype), x_t))
            dp = jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype),
                              params)
            d_pos = np.zeros(np.shape(pos_p), jax.dtypes.float0)
            return dx, dp, d_pos

        run_d.defvjp(run_d_fwd, run_d_bwd)
        return _to_host(run_d(x_t, layer_params, pos_p))
    if "dummybwd" in _bis:
        def run_bwd_dummy(res, d_out_t):
            import numpy as np
            x_t, params, *_ = res
            dx = jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype), x_t)
            dx = _to_host(dx)
            dp = jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype), params)
            d_pos = np.zeros(np.shape(pos_p), jax.dtypes.float0)
            return dx, dp, d_pos
        run.defvjp(run_fwd, run_bwd_dummy)
        return run(x_t, layer_params, pos_p)
    run.defvjp(run_fwd, run_bwd)
    return run(x_t, layer_params, pos_p)
