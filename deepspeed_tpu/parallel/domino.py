"""Domino: tensor parallelism with communication hidden behind compute.

Reference: ``deepspeed/runtime/domino/transformer.py:411``
(``DominoTransformer``) + ``domino/async_linear.py:47``
(``DominoAsyncColumnParallelLinear``) — row-split the batch into two
micro-chunks; launch chunk k's TP allreduce asynchronously and overlap it
with chunk k+1's compute, hiding up to 100% of TP communication.

TPU-native: XLA's latency-hiding scheduler overlaps a collective with any
compute that doesn't depend on it — what Domino engineers with CUDA
streams falls out of *graph structure* here. This module provides the
structure: the layer processes ``num_chunks`` independent batch slices
whose collective/compute chains don't depend on each other, so while
chunk 0's psum (after the row-parallel matmul) is on the ICI wire, chunk
1's column-parallel matmuls occupy the MXU. The explicit shard_map +
psum form (rather than GSPMD constraints) pins the collective placement
to exactly the Domino schedule.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel import topology
from deepspeed_tpu.utils.comms_logging import get_comms_logger
from deepspeed_tpu.utils import jaxcompat

BATCH_SPEC = P(("dp", "fsdp", "ep"))


def _layer_norm(x, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps)


def _chunk_attention(q, k, v, causal: bool):
    # local heads only (column-sharded qkv): plain sdpa per chunk
    d = q.shape[-1]
    scores = jnp.einsum("bsnd,btnd->bnst", q, k) / jnp.sqrt(
        jnp.asarray(d, q.dtype))
    if causal:
        s, t = scores.shape[-2:]
        mask = jnp.tril(jnp.ones((s, t), bool))
        scores = jnp.where(mask, scores, jnp.asarray(-1e30, scores.dtype))
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bnst,btnd->bsnd", probs, v)


def domino_layer_params(rng, hidden: int, ffn: int, num_heads: int,
                        dtype=jnp.bfloat16) -> Dict[str, Any]:
    """Weights for one Domino transformer layer ([in, out] layout)."""
    ks = jax.random.split(rng, 4)
    s = hidden ** -0.5
    return {
        "wqkv": (jax.random.normal(ks[0], (hidden, 3 * hidden)) * s
                 ).astype(dtype),
        "wo": (jax.random.normal(ks[1], (hidden, hidden)) * s).astype(dtype),
        "w1": (jax.random.normal(ks[2], (hidden, ffn)) * s).astype(dtype),
        "w2": (jax.random.normal(ks[3], (ffn, hidden)) * (ffn ** -0.5)
               ).astype(dtype),
    }


def _local_layer(params, x, *, num_heads: int, num_chunks: int,
                 causal: bool, tp_axis: str):
    """Runs inside shard_map: x [B_loc, S, H] full hidden; weights are the
    local TP shards (wqkv/w1 column = [H, 3H/p | F/p], wo/w2 row =
    [H/p, H | F→H])."""
    tp = jax.lax.psum(1, tp_axis)
    del tp
    B = x.shape[0]
    n_local = params["wqkv"].shape[1] // 3 // (x.shape[-1] // num_heads)
    hd = x.shape[-1] // num_heads

    chunks = jnp.split(x, num_chunks, axis=0)
    # phase 1: per-chunk attention up to the row-parallel projection —
    # each chunk ends in its own psum; chunks are mutually independent so
    # XLA overlaps chunk k+1's matmuls with chunk k's psum (the Domino
    # async-allreduce schedule).
    attn_out = []
    for cx in chunks:
        y = _layer_norm(cx)
        qkv = y @ params["wqkv"]  # column-parallel: [b, s, 3*Hl]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(*q.shape[:2], n_local, hd)
        k = k.reshape(*k.shape[:2], n_local, hd)
        v = v.reshape(*v.shape[:2], n_local, hd)
        o = _chunk_attention(q, k, v, causal)
        o = o.reshape(*o.shape[:2], n_local * hd)
        partial = o @ params["wo"]  # row-parallel partial sums
        full = jax.lax.psum(partial, tp_axis)
        attn_out.append(cx + full)

    # phase 2: per-chunk MLP, same overlap structure
    out = []
    for cx in attn_out:
        y = _layer_norm(cx)
        h = jax.nn.gelu(y @ params["w1"])  # column-parallel
        partial = h @ params["w2"]  # row-parallel
        full = jax.lax.psum(partial, tp_axis)
        out.append(cx + full)
    return jnp.concatenate(out, axis=0)


def domino_transformer_layer(params, x, *, num_heads: int,
                             num_chunks: int = 2, causal: bool = True,
                             tp_axis: str = "tp",
                             mesh=None) -> jax.Array:
    """One TP transformer layer with the Domino chunked schedule.

    params: domino_layer_params output, *unsharded* (global); x: [B, S, H]
    batch-sharded. The weights are sharded here (column specs for
    wqkv/w1, row specs for wo/w2) and the body runs under shard_map with
    explicit psums.
    """
    mesh = mesh or topology._GLOBAL_MESH
    if mesh is None or mesh.shape.get(tp_axis, 1) == 1:
        # single-chip fallback: same math, no collectives
        return _single_device_layer(params, x, num_heads=num_heads,
                                    causal=causal)
    get_comms_logger().record(
        "all_reduce", 2 * x.size * x.dtype.itemsize, tp_axis,
        log_name="domino_layer_allreduce")
    wspecs = {"wqkv": P(None, tp_axis), "wo": P(tp_axis, None),
              "w1": P(None, tp_axis), "w2": P(tp_axis, None)}
    fn = jaxcompat.shard_map(
        functools.partial(_local_layer, num_heads=num_heads,
                          num_chunks=num_chunks, causal=causal,
                          tp_axis=tp_axis),
        mesh=mesh,
        in_specs=(wspecs, BATCH_SPEC),
        out_specs=BATCH_SPEC,
        check_vma=False)
    return fn(params, x)


def _single_device_layer(params, x, *, num_heads: int, causal: bool):
    hd = x.shape[-1] // num_heads
    y = _layer_norm(x)
    qkv = y @ params["wqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(*q.shape[:2], num_heads, hd)
    k = k.reshape(*k.shape[:2], num_heads, hd)
    v = v.reshape(*v.shape[:2], num_heads, hd)
    o = _chunk_attention(q, k, v, causal).reshape(x.shape)
    x = x + o @ params["wo"]
    y = _layer_norm(x)
    return x + jax.nn.gelu(y @ params["w1"]) @ params["w2"]


class DominoTransformer:
    """Stack of Domino layers (reference DominoTransformer
    domino/transformer.py:411)."""

    def __init__(self, num_layers: int, hidden: int, ffn: int,
                 num_heads: int, num_chunks: int = 2, causal: bool = True,
                 dtype=jnp.bfloat16):
        self.num_layers = num_layers
        self.hidden = hidden
        self.ffn = ffn
        self.num_heads = num_heads
        self.num_chunks = num_chunks
        self.causal = causal
        self.dtype = dtype

    def init(self, rng):
        return [domino_layer_params(k, self.hidden, self.ffn,
                                    self.num_heads, self.dtype)
                for k in jax.random.split(rng, self.num_layers)]

    def apply(self, params, x, mesh=None):
        for layer in params:
            x = domino_transformer_layer(
                layer, x, num_heads=self.num_heads,
                num_chunks=self.num_chunks, causal=self.causal, mesh=mesh)
        return x

    __call__ = apply
