"""Experiment monitoring backends.

Analog of the reference monitor subsystem (deepspeed/monitor/monitor.py:30
``MonitorMaster`` fanning out to TensorBoard/WandB/Comet/CSV). Events are
``(label, value, step)`` triples written only from process 0 (the
reference writes from rank 0 of each relevant group).
"""

from __future__ import annotations

import csv
import os
from typing import List, Optional, Tuple

import jax

from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, float, int]


class _Backend:
    enabled = False

    def write_events(self, events: List[Event]):
        raise NotImplementedError


class CSVMonitor(_Backend):
    """reference: monitor/csv_monitor.py"""

    def __init__(self, cfg):
        self.enabled = cfg.enabled
        self.output_path = cfg.output_path or "./csv_monitor"
        self.job_name = cfg.job_name
        self._files = {}
        if self.enabled:
            os.makedirs(os.path.join(self.output_path, self.job_name),
                        exist_ok=True)

    def write_events(self, events: List[Event]):
        for label, value, step in events:
            fname = os.path.join(self.output_path, self.job_name,
                                 label.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", label])
                w.writerow([step, value])


class TensorBoardMonitor(_Backend):
    """reference: monitor/tensorboard.py — uses torch's pure-python
    SummaryWriter (torch-cpu is available on TPU hosts)."""

    def __init__(self, cfg):
        self.enabled = False
        if not cfg.enabled:
            return
        try:
            from torch.utils.tensorboard import SummaryWriter

            path = os.path.join(cfg.output_path or "./runs", cfg.job_name)
            self.writer = SummaryWriter(log_dir=path)
            self.enabled = True
        except Exception as e:
            logger.warning(f"tensorboard monitor unavailable: {e}")

    def write_events(self, events: List[Event]):
        for label, value, step in events:
            self.writer.add_scalar(label, value, step)
        self.writer.flush()


class WandbMonitor(_Backend):
    """reference: monitor/wandb.py — wandb is not in the image; gated."""

    def __init__(self, cfg):
        self.enabled = False
        if not cfg.enabled:
            return
        try:
            import wandb

            wandb.init(project=cfg.project, group=cfg.group, name=cfg.job_name)
            self._wandb = wandb
            self.enabled = True
        except Exception as e:
            logger.warning(f"wandb monitor unavailable: {e}")

    def write_events(self, events: List[Event]):
        for label, value, step in events:
            self._wandb.log({label: value}, step=step)


class CometMonitor(_Backend):
    """reference: monitor/comet.py — comet_ml is not in the image; gated."""

    def __init__(self, cfg):
        self.enabled = False
        if not cfg.enabled:
            return
        try:
            import comet_ml

            self._exp = comet_ml.Experiment(project_name=cfg.project)
            if cfg.job_name:
                self._exp.set_name(cfg.job_name)
            self.enabled = True
        except Exception as e:
            logger.warning(f"comet monitor unavailable: {e}")

    def write_events(self, events: List[Event]):
        for label, value, step in events:
            self._exp.log_metric(label, value, step=step)


class JSONLMonitor(_Backend):
    """Append-only JSON-lines event stream (observability hub sink
    reused as a monitor backend: one `{"label", "value", "step"}` row
    per event, greppable and pandas-loadable without a TB install)."""

    def __init__(self, cfg):
        self.enabled = False
        if not cfg.enabled:
            return
        from deepspeed_tpu.observability.sinks import JSONLSink

        path = cfg.output_path or "./monitor_events.jsonl"
        if os.path.isdir(path) or path.endswith(os.sep):
            path = os.path.join(path, cfg.job_name + ".jsonl")
        self._sink = JSONLSink(path)
        self.enabled = True

    def write_events(self, events: List[Event]):
        for label, value, step in events:
            self._sink.write({"kind": "monitor_event", "label": label,
                              "value": value, "step": step})


class MonitorMaster:
    """Fan-out writer (reference monitor/monitor.py:30)."""

    def __init__(self, monitor_config):
        self.backends: List[_Backend] = []
        if jax.process_index() == 0:
            for backend_cls, cfg in (
                (TensorBoardMonitor, monitor_config.tensorboard),
                (CSVMonitor, monitor_config.csv_monitor),
                (WandbMonitor, monitor_config.wandb),
                (CometMonitor, monitor_config.comet),
                (JSONLMonitor, getattr(monitor_config, "jsonl", None)),
            ):
                if cfg is None:
                    continue
                b = backend_cls(cfg)
                if b.enabled:
                    self.backends.append(b)

    @property
    def enabled(self) -> bool:
        return bool(self.backends)

    def write_events(self, events: List[Event]):
        for b in self.backends:
            b.write_events(events)
