"""Double-buffered file writer over the native AIO library.

Analog of the reference ``FastFileWriter`` (deepspeed/io/
fast_file_writer.py:44): data is staged into pinned buffers and written
by the async I/O handle while the caller fills the next buffer, so
serialization and disk I/O pipeline. Falls back to buffered ``write``
when the native library is unavailable.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from deepspeed_tpu.ops.native.aio import (AsyncIOHandle, PinnedBuffer,
                                          DEFAULT_BLOCK_SIZE)


@dataclass
class FastFileWriterStats:
    """Reference: FastFileWriter._dump_state counters."""

    bytes_written: int = 0
    write_calls: int = 0
    flushes: int = 0


class FastFileWriter:
    """Sequential writer with two pinned staging buffers.

    write() copies into the active buffer; when full, the buffer is
    handed to the aio handle (async) and the other buffer becomes
    active — waiting only if *it* still has an outstanding write.
    """

    def __init__(self, path: str, buffer_size: int = 8 * DEFAULT_BLOCK_SIZE,
                 aio_handle: Optional[AsyncIOHandle] = None):
        self.path = path
        self.buffer_size = int(buffer_size)
        self._aio = aio_handle or AsyncIOHandle()
        self._bufs = [PinnedBuffer(self.buffer_size, dtype=np.uint8)
                      for _ in range(2)]
        self._pending = [False, False]  # buffer handed to aio, not waited
        self._active = 0
        self._fill = 0  # bytes staged in the active buffer
        self._offset = 0  # file offset of the next submitted write
        self.stats = FastFileWriterStats()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        # truncate up front so a crash mid-write can't leave stale tail data
        with open(path, "wb"):
            pass
        self._closed = False

    # ------------------------------------------------------------------
    def write(self, data: bytes) -> int:
        assert not self._closed, "write after close"
        view = memoryview(data)
        while len(view):
            room = self.buffer_size - self._fill
            take = min(room, len(view))
            dst = self._bufs[self._active].array
            dst[self._fill:self._fill + take] = np.frombuffer(
                view[:take], dtype=np.uint8)
            self._fill += take
            view = view[take:]
            if self._fill == self.buffer_size:
                self._swap()
        self.stats.write_calls += 1
        self.stats.bytes_written += len(data)
        return len(data)

    def _swap(self):
        """Submit the active buffer and rotate."""
        if self._fill == 0:
            return
        buf = self._bufs[self._active]
        self._aio.async_pwrite(buf.array[: self._fill], self.path,
                               offset=self._offset)
        self._pending[self._active] = True
        self._offset += self._fill
        self._active ^= 1
        self._fill = 0
        if self._pending[self._active]:
            # the buffer we are about to fill is still in flight from two
            # swaps ago: drain before reusing it (double, not triple,
            # buffering). wait() drains the whole queue.
            self._drain()

    def _drain(self):
        errors = self._aio.wait()
        self._pending = [False, False]
        if errors:
            raise IOError(
                f"{errors} async write(s) to {self.path} failed "
                "(disk full or I/O error) — file is incomplete")

    def flush(self):
        self._swap()
        self._drain()
        self.stats.flushes += 1

    def close(self):
        if self._closed:
            return
        try:
            self.flush()
        finally:
            self._closed = True
            # a failed flush can leave writes in flight — the native
            # threads still read from the pinned buffers, so they must be
            # drained (best-effort) before the memory is freed
            try:
                if any(self._pending):
                    try:
                        self._aio.wait()
                    except Exception:
                        pass
                    self._pending = [False, False]
            finally:
                for b in self._bufs:
                    b.free()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
        return False
