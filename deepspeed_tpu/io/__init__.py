from deepspeed_tpu.io.fast_file_writer import FastFileWriter

__all__ = ["FastFileWriter"]
