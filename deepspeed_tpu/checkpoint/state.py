"""Engine checkpoint save/load.

Analog of the reference's engine checkpoint path (engine.py:4557
``save_checkpoint``, :4079 ``load_checkpoint``) with the same on-disk
contract: a ``latest`` tag file, per-tag directories, tag-validation, and
client state. The tensor payload uses orbax (sharded, multi-host-safe,
async-capable) instead of per-rank torch.save files.

**Elastic + universal checkpointing are inherent here**: orbax stores
*global* arrays with their shardings, and restore takes an abstract tree
with *target* shardings — so resuming on a different dp/fsdp/tp topology
is just a restore with the new plan's shardings. The reference needs
offset arithmetic across flat partitions for this
(ds_to_universal.py:121-249, stage_1_and_2.py:2567 elastic load); here it
is a property of named sharding. See checkpoint/universal.py for the
inspection/conversion CLIs.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional, Tuple

import jax

from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.version import __version__

LATEST_FILE = "latest"
METADATA_FILE = "metadata.json"
STATE_DIR = "state"


def _is_primary() -> bool:
    return jax.process_index() == 0


class CheckpointIO:
    """Bound to an Engine; owns its save/load."""

    def __init__(self, engine):
        self.engine = engine
        from deepspeed_tpu.runtime.checkpoint_engine import \
            make_checkpoint_engine

        self.ckpt_engine = make_checkpoint_engine(engine.config.checkpoint)
        self._pending_commit = None  # (tag, save_dir, ckpt_dir, meta, latest)
        # a final async save with no later step/save/load would otherwise
        # never publish metadata + 'latest' — commit at interpreter exit
        import atexit
        import weakref

        ref = weakref.ref(self)

        def _commit_at_exit():
            obj = ref()  # bind once: the object can be collected mid-expr
            if obj is not None:
                obj.commit_pending()

        atexit.register(_commit_at_exit)

    # -- state tree ----------------------------------------------------
    def _state(self) -> Dict[str, Any]:
        e = self.engine
        state = {
            "params": e.params,
            "step_count": e.step_count,
            "loss_scale": e.loss_scale_state,
        }
        if e.opt_state is not None:  # offload keeps optimizer state on host
            state["opt_master"] = e.opt_state.master
            state["opt_inner"] = e.opt_state.inner
        if getattr(e, "_onebit_state", None) is not None:
            state["onebit"] = e._onebit_state
        if getattr(e, "_zeropp_state", None) is not None:
            state["zeropp"] = e._zeropp_state
        return state

    def _abstract_state(self) -> Dict[str, Any]:
        def absify(x):
            if isinstance(x, jax.Array):
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            return x

        return jax.tree.map(absify, self._state())

    # -- save ----------------------------------------------------------
    def save(self, save_dir: str, tag: Optional[str] = None,
             client_state: Optional[Dict] = None, save_latest: bool = True):
        e = self.engine
        self.commit_pending()  # at most one async save in flight
        tag = tag or f"global_step{e.global_steps}"
        ckpt_dir = os.path.join(os.path.abspath(save_dir), str(tag))
        os.makedirs(ckpt_dir, exist_ok=True)

        self.ckpt_engine.create(str(tag))
        self.ckpt_engine.save(os.path.join(ckpt_dir, STATE_DIR), self._state())

        if getattr(e, "_zenflow", None) is not None:
            # ZenFlow owns the masters when active (the HostOffload
            # instance's copies are stale — saving them would restore a
            # rollback); snapshot the whole importance-split state
            import numpy as np

            dst = os.path.join(
                ckpt_dir, f"zenflow_rank{jax.process_index()}.npy")
            np.save(dst, np.asarray(e._zenflow.state_dict(),
                                    dtype=object), allow_pickle=True)
        elif getattr(e, "_offload", None) is not None:
            # host-resident optimizer shards: one npz per process
            # (reference: per-dp-rank zero checkpoint files engine.py:4003)
            import numpy as np

            sd = e._offload.state_dict()
            flat = {}
            for key, entry in sd.items():
                for field, val in entry.items():
                    flat[f"{key}##{field}"] = np.asarray(val)
            dst = os.path.join(
                ckpt_dir, f"offload_optim_rank{jax.process_index()}.npz")
            if hasattr(self.ckpt_engine, "save_host_blob"):
                # fast engine: np.savez streams zip members straight into
                # the double-buffered AIO writer — serialization overlaps
                # disk I/O and peak extra memory is one staging buffer
                self.ckpt_engine.save_host_blob(
                    lambda f: np.savez(f, **flat), dst)
            else:
                # np.savez appends '.npz' unless the path already ends in it
                tmp = f"{dst}.{os.getpid()}.tmp.npz"
                np.savez(tmp, **flat)
                os.replace(tmp, dst)  # atomic: no half-written rank files

        # data-pipeline cursor: consumed GAS boundaries + loader state,
        # snapshotted at this (drained) boundary so auto-resume replays
        # the exact remaining batch stream (resilience/resume.py)
        try:
            from deepspeed_tpu.resilience.resume import data_cursor
            cursor = data_cursor(e)
        except Exception as err:
            logger.warning(f"data cursor snapshot failed: {err}")
            cursor = {}
        meta = {
            "tag": str(tag),
            "framework_version": __version__,
            "saved_at": time.time(),
            "global_steps": e.global_steps,
            "global_samples": e.global_samples,
            "skipped_steps": e.skipped_steps,
            "mesh_shape": {k: int(v) for k, v in e.mesh.shape.items()},
            "world_size": jax.process_count(),
            "zero_stage": e.config.zero_optimization.stage,
            "data_cursor": cursor,
            "config": e.config.to_dict(),
            "client_state": client_state or {},
        }
        from deepspeed_tpu.runtime.checkpoint_engine import \
            DecoupledCheckpointEngine

        if isinstance(self.ckpt_engine, DecoupledCheckpointEngine):
            # decoupled: 'latest' is published at commit (next GAS boundary
            # or the next save/load), reference engine.py:3273
            self._pending_commit = (str(tag), save_dir, ckpt_dir, meta,
                                    save_latest)
            log_dist(f"checkpoint save in flight: {ckpt_dir}", ranks=[0])
            return ckpt_dir
        self._publish(str(tag), save_dir, ckpt_dir, meta, save_latest)
        log_dist(f"saved checkpoint: {ckpt_dir}", ranks=[0])
        return ckpt_dir

    def _publish(self, tag, save_dir, ckpt_dir, meta, save_latest):
        """Barrier + metadata + manifest + 'latest' pointer — only after
        every rank's payload is durable, or a preemption could leave
        'latest' pointing at a checkpoint that cannot restore on some
        ranks. Ordering matters: the manifest (the durability witness)
        goes down before 'latest', so 'latest' never names a checkpoint
        without one."""
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(f"ckpt_save_{tag}")
        if _is_primary():
            with open(os.path.join(ckpt_dir, METADATA_FILE), "w") as f:
                json.dump(meta, f, indent=2, default=str)
            rcfg = getattr(self.engine.config, "resilience", None)
            if rcfg is None or (rcfg.enabled and rcfg.manifest):
                from deepspeed_tpu.resilience.manifest import write_manifest

                write_manifest(
                    ckpt_dir, tag,
                    global_steps=int(meta.get("global_steps", 0)),
                    world={
                        "mesh_shape": meta.get("mesh_shape", {}),
                        "process_count": jax.process_count(),
                        "device_count": jax.device_count(),
                    },
                    data_cursor=meta.get("data_cursor", {}))
            if save_latest:
                with open(os.path.join(os.path.abspath(save_dir),
                                       LATEST_FILE), "w") as f:
                    f.write(str(tag))

    @staticmethod
    def _agree(done: bool, failed: bool) -> Tuple[bool, bool]:
        """All-process agreement on (all done, any failed). Every rank with
        a pending commit calls this in lockstep (same save ⇒ same polling
        sequence), so the collective never mismatches."""
        if jax.process_count() == 1:
            return done, failed
        import numpy as np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([bool(done), bool(failed)]))
        return bool(np.all(flags[:, 0])), bool(np.any(flags[:, 1]))

    def commit_pending(self):
        """Block until an in-flight async save is durable, then publish.

        A rank whose write failed must not leave the others stuck in the
        publish barrier: ranks first agree on success, and on any failure
        everyone abandons the pending save (the failing rank re-raises)."""
        if self._pending_commit is None:
            return
        tag, save_dir, ckpt_dir, meta, save_latest = self._pending_commit
        self._pending_commit = None
        err = None
        try:
            self.ckpt_engine.commit(tag)
        except Exception as e:  # noqa: BLE001 — agreed on below
            err = e
        _, any_failed = self._agree(True, err is not None)
        if any_failed:
            if err is not None:
                raise err
            raise RuntimeError(
                f"async checkpoint '{tag}' failed on another rank; "
                "not publishing")
        self._publish(tag, save_dir, ckpt_dir, meta, save_latest)
        log_dist(f"saved checkpoint: {ckpt_dir}", ranks=[0])

    def maybe_commit(self):
        """Polled at GAS boundaries (reference engine.py:3273).

        Multi-host: ranks finish their async writes at different times, and
        ``_publish`` runs a global barrier — so all processes must agree the
        save is done *before* anyone enters it, or one rank blocks in the
        barrier while another issues the next step's collectives (deadlock).
        A rank-local write error is folded into the agreement the same way
        (a raise before the all-gather would strand the other ranks)."""
        if self._pending_commit is None:
            return
        err = None
        try:
            done = self.ckpt_engine.maybe_finalize()
        except Exception as e:  # noqa: BLE001 — agreed on below
            err, done = e, True
        done, any_failed = self._agree(done, err is not None)
        if any_failed:
            self._pending_commit = None
            if err is not None:
                raise err
            raise RuntimeError(
                "async checkpoint save failed on another rank; pending "
                "save abandoned")
        if done:
            self.commit_pending()

    # -- load ----------------------------------------------------------
    def load(self, load_dir: str, tag: Optional[str] = None,
             load_optimizer_states: bool = True
             ) -> Tuple[Optional[str], Optional[Dict]]:
        e = self.engine
        self.commit_pending()
        if e.config.checkpoint.load_universal:
            from deepspeed_tpu.checkpoint.universal import load_universal

            load_universal(e, load_dir,
                           load_optimizer_states=load_optimizer_states)
            return os.path.abspath(load_dir), {}
        load_dir = os.path.abspath(load_dir)
        if tag is None:
            latest = os.path.join(load_dir, LATEST_FILE)
            if os.path.exists(latest):
                with open(latest) as f:
                    tag = f.read().strip()
        ckpt_dir = os.path.join(load_dir, str(tag)) if tag else ""
        dir_ok = bool(tag) and os.path.isdir(ckpt_dir)
        meta: Dict[str, Any] = {}
        if dir_ok:
            meta_path = os.path.join(ckpt_dir, METADATA_FILE)
            if os.path.exists(meta_path):
                with open(meta_path) as f:
                    meta = json.load(f)
        # manifest validation (resilience/manifest.py): a torn or corrupt
        # save must be REFUSED here, before any tensor restore — a silent
        # bad restore is worse than a failed one. Validity is computed
        # per-host but folded into the cross-process assert below so all
        # ranks take the same accept/fallback path in lockstep.
        manifest_doc = manifest_err = None
        rcfg = getattr(e.config, "resilience", None)
        check_manifest = rcfg is None or (rcfg.enabled and rcfg.manifest)
        if dir_ok and check_manifest:
            from deepspeed_tpu.resilience.manifest import (
                CheckpointCorruptError, validate_manifest)

            try:
                manifest_doc = validate_manifest(
                    ckpt_dir,
                    check_checksums=(rcfg is None
                                     or rcfg.manifest_checksums))
            except CheckpointCorruptError as err:
                manifest_err = err
        # multi-host: every process must see the SAME checkpoint (a
        # skewed shared-filesystem view or per-host load_dir typo
        # otherwise desynchronizes training silently — reference
        # _checkpoint_tag_validation engine.py:4540 +
        # assert_ints_same_as_other_ranks). The collective runs BEFORE
        # any per-host early return/raise, or the disagreeing host
        # would bail out and leave its peers hung inside it.
        from deepspeed_tpu import comm as _comm

        _comm.assert_same_across_processes(
            "checkpoint_load",
            [str(tag) if tag else "<missing-latest>", int(dir_ok),
             int(meta.get("global_steps", -1)),
             int(load_optimizer_states), int(manifest_err is None)])
        if tag is None:
            logger.warning(f"no '{LATEST_FILE}' file at {load_dir}; "
                           "nothing loaded")
            return None, None
        if not dir_ok:
            raise FileNotFoundError(f"checkpoint not found: {ckpt_dir}")
        if manifest_err is not None:
            from deepspeed_tpu.resilience.manifest import \
                find_latest_valid_tag
            from deepspeed_tpu.utils import telemetry

            telemetry.count("resilience.corrupt_checkpoint",
                            reason=str(tag))
            fallback = find_latest_valid_tag(
                load_dir, exclude=[str(tag)],
                check_checksums=(rcfg is None or rcfg.manifest_checksums))
            if fallback is None:
                raise manifest_err
            logger.error(
                f"checkpoint '{tag}' failed manifest validation "
                f"({manifest_err.reason}); falling back to the previous "
                f"good tag '{fallback}'")
            return self.load(load_dir, tag=fallback,
                             load_optimizer_states=load_optimizer_states)
        if manifest_doc is None and check_manifest:
            logger.warning(
                f"checkpoint '{tag}' has no manifest (saved before the "
                "resilience subsystem, or by a non-primary writer): "
                "accepting without integrity verification")
        self._validate_tag(meta, tag)

        abstract = self._abstract_state()
        state_path = os.path.join(ckpt_dir, STATE_DIR)
        if not load_optimizer_states:
            # don't read optimizer payloads (~3x param bytes) only to
            # discard them — the re-seed paths below rebuild from params
            from deepspeed_tpu.runtime.checkpoint_engine import load_partial

            subset = dict(abstract)
            for key in ("opt_master", "opt_inner", "zeropp", "onebit"):
                subset.pop(key, None)
            try:
                restored = load_partial(state_path, subset)
            except Exception as err:  # fall back to a full read
                logger.warning(f"partial restore unavailable ({err}); "
                               "reading the full checkpoint")
                restored = self.ckpt_engine.load(state_path, abstract)
        else:
            restored = self.ckpt_engine.load(state_path, abstract)

        e.params = restored["params"]
        if getattr(e, "_zeropp_state", None) is not None:
            if load_optimizer_states and "zeropp" in restored:
                e._zeropp_state = restored["zeropp"]
            else:
                # no optimizer state requested/present: re-seed the fp32
                # masters from the restored params or the next step's
                # all-gather would roll the model back to init (same
                # hazard as the offload reinit_masters path below)
                from deepspeed_tpu.runtime.zeropp import \
                    reseed_state_from_params

                logger.warning(
                    "ZeRO++ state not restored: masters re-seeded from "
                    "params, moments reset")
                new = reseed_state_from_params(
                    e.params, e._zeropp_state, e.mesh.shape["dp"])
                e._zeropp_state = jax.tree.map(
                    lambda x, old: jax.device_put(x, old.sharding),
                    new, e._zeropp_state)
        if getattr(e, "_onebit_state", None) is not None:
            if load_optimizer_states and "onebit" in restored:
                e._onebit_state = restored["onebit"]
            else:
                # same rollback hazard as the paths below: the 1-bit
                # masters drive the next update, so re-seed from params
                import jax.numpy as jnp

                from deepspeed_tpu.runtime.onebit import OneBitState

                logger.warning("1-bit optimizer state not restored: "
                               "masters re-seeded from params, moments "
                               "and error feedback reset")
                st = e._onebit_state
                master_sh = jax.tree.map(lambda a: a.sharding, st.master)
                master = jax.jit(
                    lambda p: jax.tree.map(
                        lambda x: x.astype("float32"), p),
                    out_shardings=master_sh)(e.params)
                e._onebit_state = OneBitState(
                    master=master,
                    m=jax.tree.map(jnp.zeros_like, st.m),
                    v=jax.tree.map(jnp.zeros_like, st.v),
                    error=jax.tree.map(jnp.zeros_like, st.error),
                    step=st.step)
        if getattr(e, "_zenflow", None) is not None:
            import numpy as np

            zf_path = os.path.join(
                ckpt_dir, f"zenflow_rank{jax.process_index()}.npy")
            from deepspeed_tpu import comm as _comm

            # per-rank file: agree collectively, then fail on ALL ranks
            # (one rank raising alone would hang its peers' collectives)
            if _comm.any_process(load_optimizer_states
                                 and not os.path.exists(zf_path)):
                # ADVICE r1: the user asked for optimizer state — a
                # silent rebuild (fresh moments, bf16-rounded masters)
                # is a degraded resume; fail like the offload branch
                raise FileNotFoundError(
                    f"zenflow optimizer state missing on at least one "
                    f"process (this rank's path: {zf_path}). Pass "
                    "load_optimizer_states=False to knowingly re-seed "
                    "fresh importance-split state from the restored "
                    "params")
            if load_optimizer_states and os.path.exists(zf_path):
                e._zenflow.load_state_dict(
                    np.load(zf_path, allow_pickle=True).item())
            else:
                # rebuild importance-split state from the restored params
                from deepspeed_tpu.runtime.zenflow import ZenFlowOptimizer

                e._zenflow = ZenFlowOptimizer(e.params, e._zenflow.cfg,
                                              lr=e._zenflow.lr)
        elif getattr(e, "_offload", None) is not None:
            import numpy as np

            path = os.path.join(
                ckpt_dir, f"offload_optim_rank{jax.process_index()}.npz")
            if load_optimizer_states and os.path.exists(path):
                data = np.load(path)
                sd: Dict[str, Dict[str, Any]] = {}
                for flat_key in data.files:
                    key, field = flat_key.split("##", 1)
                    sd.setdefault(key, {})[field] = data[flat_key]
                e._offload.load_state_dict(sd)
                e.params = e._jit_reshard_to_params(
                    e._offload.sync_params_from_masters(e.params))
            elif load_optimizer_states:
                raise FileNotFoundError(
                    f"offload optimizer state missing at {path} — the host "
                    "masters would silently overwrite the restored params on "
                    "the next step. Pass load_optimizer_states=False to "
                    "rebuild masters (zeroed moments) from the checkpoint "
                    "params instead.")
            else:
                # no optimizer state requested: masters must still be
                # re-seeded from the restored params or the next step would
                # roll the model back to init.
                e._offload.reinit_masters(
                    e._jit_to_opt_sharding(jax.tree.map(
                        lambda x: x.astype("float32"), e.params)))
        elif e.opt_state is not None:
            from deepspeed_tpu.runtime.optimizer import (MixedPrecisionState,
                                                         init_mixed_precision)

            if load_optimizer_states and "opt_master" in restored:
                e.opt_state = MixedPrecisionState(
                    master=restored["opt_master"],
                    inner=restored["opt_inner"])
            else:
                # masters drive the next update — re-seed them from the
                # restored params or the step rolls the model back to init
                logger.warning("optimizer state not restored: masters "
                               "re-seeded from params, moments reset")
                opt_sh = jax.tree.map(lambda a: a.sharding,
                                      e.opt_state.master)
                p32 = jax.jit(
                    lambda p: jax.tree.map(
                        lambda x: x.astype("float32"), p),
                    out_shardings=opt_sh)(e.params)
                e.opt_state = init_mixed_precision(p32, e.tx)
        e.step_count = restored["step_count"]
        e.loss_scale_state = restored["loss_scale"]
        e.global_steps = int(meta.get("global_steps", int(e.step_count)))
        e.global_samples = int(meta.get("global_samples", 0))
        e.skipped_steps = int(meta.get("skipped_steps", 0))
        # data-pipeline cursor for deterministic auto-resume
        # (engine.resume_data_iter / resilience/resume.py); the manifest
        # copy wins — it is only written for fully-durable saves
        e.loaded_data_cursor = ((manifest_doc or {}).get("data_cursor")
                                or meta.get("data_cursor") or None)
        log_dist(f"loaded checkpoint: {ckpt_dir} (tag={tag})", ranks=[0])
        return ckpt_dir, meta.get("client_state", {})

    def _validate_tag(self, meta: Dict, tag: str):
        """Reference _checkpoint_tag_validation (engine.py:4540): ensure
        the tag is consistent; here also surface topology change (which
        is legal — orbax reshards — but must never be silent: explicit
        log + ``resilience.resharded_restore`` telemetry, and when the
        config is elastic the batch math is re-checked for the new world
        so a reshard onto an invalid node count fails at load, not ten
        steps into a wrong-batch run)."""
        if not meta:
            return
        e = self.engine
        saved_mesh = meta.get("mesh_shape")
        cur_mesh = {k: int(v) for k, v in e.mesh.shape.items()}
        if not saved_mesh or saved_mesh == cur_mesh:
            return
        from deepspeed_tpu.utils import telemetry

        telemetry.count("resilience.resharded_restore",
                        reason=f"{saved_mesh} -> {cur_mesh}")
        logger.warning(
            f"resharded restore: checkpoint '{tag}' was saved on mesh "
            f"{saved_mesh} (world_size {meta.get('world_size', '?')}), "
            f"loading onto {cur_mesh} (world_size {jax.process_count()})")
        ecfg = (meta.get("config") or {}).get("elasticity") \
            or e.config.to_dict().get("elasticity")
        if ecfg and ecfg.get("enabled", False):
            from deepspeed_tpu.elasticity.elasticity import (
                ElasticityError, compute_elastic_config)

            try:
                compute_elastic_config({"elasticity": dict(ecfg)},
                                       target_deployment_size=int(
                                           e.dp_world_size))
            except ElasticityError as err:
                raise ValueError(
                    f"resharded restore rejected: elastic batch math "
                    f"does not hold for dp={e.dp_world_size} "
                    f"({err})") from err
        mode = e.config.checkpoint.tag_validation.lower()
        if mode == "ignore":
            return
        msg = (f"checkpoint '{tag}' was saved on mesh {saved_mesh}, "
               f"loading onto {cur_mesh}: state will be resharded")
        if mode == "fail":
            raise ValueError(msg)
        logger.warning(msg)
