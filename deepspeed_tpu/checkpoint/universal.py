"""Universal / offline checkpoint tools.

Covers the reference's offline checkpoint machinery:

  * ``get_fp32_state_dict_from_checkpoint`` / ``convert_to_fp32`` —
    ``zero_to_fp32.py`` analog (deepspeed/utils/zero_to_fp32.py):
    consolidate a (possibly topology-sharded) engine checkpoint into a
    single host fp32 state dict, without needing a device mesh or a
    running cluster. The reference stitches flat dp-rank partitions with
    offset arithmetic; orbax stores *global* arrays, so consolidation is
    just a host restore of the master tree.
  * ``convert_to_universal`` — ``ds_to_universal.py`` analog
    (deepspeed/checkpoint/ds_to_universal.py:121-249): explode the
    checkpoint into one file per parameter (fp32 master + optimizer
    moments) so any future topology/zero-stage/framework can consume it.
  * ``load_universal`` — ``load_universal_checkpoint`` analog
    (runtime/zero/stage*.py + universal_checkpoint.py:99): map a
    universal dir back onto a live engine with the *current* sharding
    plan (resharding on load).
  * ``inspect_checkpoint`` + the ``dstpu-ckpt`` CLI.

On-disk universal layout (one dir per tree path, '.'-joined):

    <out>/universal/<param-path>/fp32.npy
    <out>/universal/<param-path>/<moment-name>.npy   (exp_avg, exp_avg_sq, ...)
    <out>/universal/metadata.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, Optional, Tuple

import numpy as np

LATEST_FILE = "latest"
METADATA_FILE = "metadata.json"
STATE_DIR = "state"
UNIVERSAL_DIR = "universal"
SEP = "."


# ----------------------------------------------------------------------
# host-side restore
# ----------------------------------------------------------------------
def _resolve_tag(ckpt_root: str, tag: Optional[str]) -> str:
    if tag is None:
        latest = os.path.join(ckpt_root, LATEST_FILE)
        if not os.path.exists(latest):
            raise FileNotFoundError(
                f"no '{LATEST_FILE}' file in {ckpt_root}; pass an explicit tag")
        with open(latest) as f:
            tag = f.read().strip()
    return str(tag)


def _restore_host(ckpt_root: str, tag: Optional[str]
                  ) -> Tuple[Dict[str, Any], Dict[str, Any], str]:
    """Restore the saved tree as host numpy arrays + metadata."""
    import orbax.checkpoint as ocp

    tag = _resolve_tag(ckpt_root, tag)
    ckpt_dir = os.path.join(os.path.abspath(ckpt_root), tag)
    meta = {}
    meta_path = os.path.join(ckpt_dir, METADATA_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    with ocp.StandardCheckpointer() as ckptr:
        state = ckptr.restore(os.path.join(ckpt_dir, STATE_DIR))
    return state, meta, ckpt_dir


def _is_namedtuple(x) -> bool:
    return isinstance(x, tuple) and hasattr(x, "_fields")


def _flatten(tree, prefix="") -> Dict[str, np.ndarray]:
    """Flatten with '.'-joined keys. Namedtuples (optax states) flatten by
    FIELD NAME so live-engine trees and orbax-restored trees (which come
    back as field-name dicts) produce identical keys — the moment-name
    mapping below depends on this."""
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{SEP}{k}" if prefix else str(k)))
    elif _is_namedtuple(tree):
        for name, v in zip(tree._fields, tree):
            out.update(_flatten(v, f"{prefix}{SEP}{name}" if prefix else name))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{SEP}{i}" if prefix else str(i)))
    elif tree is None:
        pass
    else:
        out[prefix] = np.asarray(tree)
    return out


def _unflatten_into(flat: Dict[str, np.ndarray], tree, prefix=""):
    """Return a copy of ``tree`` with leaves replaced from ``flat``."""
    if isinstance(tree, dict):
        return {k: _unflatten_into(flat, v,
                                   f"{prefix}{SEP}{k}" if prefix else str(k))
                for k, v in tree.items()}
    if _is_namedtuple(tree):
        vals = [_unflatten_into(flat, v,
                                f"{prefix}{SEP}{n}" if prefix else str(n))
                for n, v in zip(tree._fields, tree)]
        return type(tree)(*vals)
    if isinstance(tree, (list, tuple)):
        vals = [_unflatten_into(flat, v,
                                f"{prefix}{SEP}{i}" if prefix else str(i))
                for i, v in enumerate(tree)]
        return tuple(vals) if isinstance(tree, tuple) else vals
    return flat.get(prefix, tree)


# ----------------------------------------------------------------------
# zero_to_fp32 analog
# ----------------------------------------------------------------------
def get_fp32_state_dict_from_checkpoint(ckpt_root: str,
                                        tag: Optional[str] = None
                                        ) -> Dict[str, np.ndarray]:
    """Single consolidated fp32 param dict (reference
    zero_to_fp32.py get_fp32_state_dict_from_zero_checkpoint)."""
    state, _meta, _dir = _restore_host(ckpt_root, tag)
    # prefer fp32 masters (exact); else cast the compute-dtype params
    src = state.get("opt_master") or state["params"]
    return {k: np.asarray(v, dtype=np.float32)
            for k, v in _flatten(src).items()}


def convert_to_fp32(ckpt_root: str, out_path: str,
                    tag: Optional[str] = None) -> str:
    """Write the consolidated fp32 dict as one .npz (zero_to_fp32 CLI)."""
    sd = get_fp32_state_dict_from_checkpoint(ckpt_root, tag)
    out_path = os.path.abspath(out_path)
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    tmp = out_path + ".tmp.npz"
    np.savez(tmp, **sd)
    os.replace(tmp, out_path)
    total = sum(v.size for v in sd.values())
    print(f"wrote {len(sd)} tensors / {total/1e6:.1f}M fp32 params -> {out_path}")
    return out_path


# ----------------------------------------------------------------------
# ds_to_universal analog
# ----------------------------------------------------------------------
def convert_to_universal(ckpt_root: str, out_dir: str,
                         tag: Optional[str] = None) -> str:
    """Explode an engine checkpoint into per-parameter files
    (reference ds_to_universal.py main: extract → merge → save)."""
    state, meta, _dir = _restore_host(ckpt_root, tag)
    out = os.path.join(os.path.abspath(out_dir), UNIVERSAL_DIR)
    os.makedirs(out, exist_ok=True)

    masters = _flatten(state.get("opt_master") or state["params"])
    moments: Dict[str, Dict[str, np.ndarray]] = {}
    # optax inner state: a tuple of stage states, each possibly holding
    # mu/nu/trace trees shaped like the params
    inner = state.get("opt_inner")
    if inner is not None:
        flat_inner = _flatten(inner)
        for key, arr in flat_inner.items():
            # key like "0.mu.<param-path>" — map moment-name per param
            parts = key.split(SEP)
            for i, p in enumerate(parts):
                if p in ("mu", "nu", "trace", "m", "v"):
                    param_path = SEP.join(parts[i + 1:])
                    name = {"mu": "exp_avg", "m": "exp_avg",
                            "nu": "exp_avg_sq", "v": "exp_avg_sq",
                            "trace": "momentum"}[p]
                    if param_path in masters and \
                            arr.shape == masters[param_path].shape:
                        moments.setdefault(param_path, {})[name] = arr
                    break

    manifest = {}
    for path, arr in masters.items():
        pdir = os.path.join(out, path)
        os.makedirs(pdir, exist_ok=True)
        np.save(os.path.join(pdir, "fp32.npy"),
                np.asarray(arr, dtype=np.float32))
        entry = {"shape": list(arr.shape), "dtype": "float32",
                 "moments": sorted(moments.get(path, {}))}
        for name, m in moments.get(path, {}).items():
            np.save(os.path.join(pdir, f"{name}.npy"),
                    np.asarray(m, dtype=np.float32))
        manifest[path] = entry

    uni_meta = {
        "source_tag": meta.get("tag"),
        "global_steps": meta.get("global_steps"),
        "step_count": int(np.asarray(state.get("step_count", 0))),
        "source_mesh_shape": meta.get("mesh_shape"),
        "zero_stage": meta.get("zero_stage"),
        "params": manifest,
    }
    with open(os.path.join(out, METADATA_FILE), "w") as f:
        json.dump(uni_meta, f, indent=2)
    print(f"wrote universal checkpoint ({len(manifest)} params) -> {out}")
    return out


def load_universal(engine, universal_dir: str,
                   load_optimizer_states: bool = True):
    """Map a universal dir onto a live engine with its current sharding
    plan (reference load_universal_checkpoint; universal_checkpoint.py:99).

    Every param found in the dir is loaded (resharded by device_put with
    the engine's target sharding); missing params keep their values.
    """
    import jax
    import jax.numpy as jnp

    root = os.path.abspath(universal_dir)
    if os.path.basename(root) != UNIVERSAL_DIR and \
            os.path.isdir(os.path.join(root, UNIVERSAL_DIR)):
        root = os.path.join(root, UNIVERSAL_DIR)
    with open(os.path.join(root, METADATA_FILE)) as f:
        meta = json.load(f)

    flat: Dict[str, np.ndarray] = {}
    for path in meta["params"]:
        flat[path] = np.load(os.path.join(root, path, "fp32.npy"))

    if engine.opt_state is not None and load_optimizer_states:
        # fp32 masters: exact restore, then recompute compute-dtype params
        new_master = _unflatten_into(flat, jax.tree.map(np.asarray,
                                                        engine.opt_state.master))
        master_sh = jax.tree.map(lambda a: a.sharding, engine.opt_state.master)
        new_master = jax.tree.map(
            lambda arr, sh: jax.device_put(np.asarray(arr, np.float32), sh),
            new_master, master_sh)
        # moments
        step_count = meta.get("step_count")

        def load_inner(old_inner):
            flat_old = _flatten(jax.tree.map(np.asarray, old_inner))
            updates: Dict[str, np.ndarray] = {}
            for key in flat_old:
                parts = key.split(SEP)
                # optimizer step counters resume at the source run's step,
                # or Adam bias correction restarts from scratch
                if parts[-1] == "count" and flat_old[key].ndim == 0 \
                        and step_count is not None:
                    updates[key] = np.asarray(step_count,
                                              flat_old[key].dtype)
                    continue
                for i, p in enumerate(parts):
                    if p in ("mu", "nu", "trace", "m", "v"):
                        param_path = SEP.join(parts[i + 1:])
                        name = {"mu": "exp_avg", "m": "exp_avg",
                                "nu": "exp_avg_sq", "v": "exp_avg_sq",
                                "trace": "momentum"}[p]
                        f = os.path.join(root, param_path, f"{name}.npy")
                        if os.path.exists(f):
                            arr = np.load(f)
                            if arr.shape == flat_old[key].shape:
                                updates[key] = arr
                        break
            return _unflatten_into({**flat_old, **updates}, old_inner) \
                if updates else None

        host_inner = jax.tree.map(np.asarray, engine.opt_state.inner)
        maybe_inner = load_inner(host_inner)
        if maybe_inner is not None:
            inner_sh = jax.tree.map(lambda a: a.sharding,
                                    engine.opt_state.inner)
            new_inner = jax.tree.map(
                lambda arr, old, sh: jax.device_put(
                    np.asarray(arr, np.asarray(old).dtype), sh),
                maybe_inner, host_inner, inner_sh)
        else:
            new_inner = engine.opt_state.inner
        from deepspeed_tpu.runtime.optimizer import MixedPrecisionState

        engine.opt_state = MixedPrecisionState(master=new_master,
                                               inner=new_inner)
        cdt = engine.compute_dtype
        engine.params = jax.jit(
            lambda m: jax.tree.map(lambda x: x.astype(cdt), m),
            out_shardings=engine._param_shardings)(new_master)
    else:
        host_params = jax.tree.map(np.asarray, engine.params)
        new_params = _unflatten_into(flat, host_params)
        engine.params = jax.tree.map(
            lambda arr, old: jax.device_put(
                np.asarray(arr, dtype=np.asarray(old).dtype), old.sharding),
            new_params, engine.params)

    step = meta.get("step_count")
    if step is not None:
        engine.step_count = jax.device_put(
            jnp.asarray(int(step), jnp.int32), engine.step_count.sharding)
        engine.global_steps = int(meta.get("global_steps") or step)
    return engine


# ----------------------------------------------------------------------
# inspection + CLI
# ----------------------------------------------------------------------
def inspect_checkpoint(ckpt_root: str, tag: Optional[str] = None) -> Dict:
    """Metadata-only: reads orbax tree metadata (shapes/dtypes), never the
    tensor payload — inspecting a multi-B-param checkpoint stays cheap."""
    import orbax.checkpoint as ocp

    tag = _resolve_tag(ckpt_root, tag)
    ckpt_dir = os.path.join(os.path.abspath(ckpt_root), tag)
    meta = {}
    meta_path = os.path.join(ckpt_dir, METADATA_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    with ocp.PyTreeCheckpointer() as ckptr:
        md = ckptr.metadata(os.path.join(ckpt_dir, STATE_DIR))
    item = getattr(md, "item_metadata", None)
    tree = getattr(item, "tree", None) or item or md
    shapes = {k: v for k, v in _flatten_meta(tree).items()}
    param_shapes = {k: v for k, v in shapes.items()
                    if k.split(SEP)[0] == "params"}
    n_params = sum(int(np.prod(s)) for s in param_shapes.values())
    return {
        "dir": ckpt_dir,
        "tag": meta.get("tag"),
        "global_steps": meta.get("global_steps"),
        "mesh_shape": meta.get("mesh_shape"),
        "zero_stage": meta.get("zero_stage"),
        "n_tensors": len(param_shapes),
        "n_params": n_params,
        "has_optimizer_state": any(k.split(SEP)[0] == "opt_master"
                                   for k in shapes),
    }


def _flatten_meta(tree, prefix="") -> Dict[str, Tuple[int, ...]]:
    """Flatten an orbax metadata tree to {path: shape}."""
    out: Dict[str, Tuple[int, ...]] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_meta(v, f"{prefix}{SEP}{k}" if prefix else str(k)))
    elif isinstance(tree, (list, tuple)) and not hasattr(tree, "shape"):
        for i, v in enumerate(tree):
            out.update(_flatten_meta(v, f"{prefix}{SEP}{i}" if prefix else str(i)))
    elif tree is None:
        pass
    else:
        out[prefix] = tuple(getattr(tree, "shape", ()) or ())
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="dstpu-ckpt",
        description="checkpoint tools: inspect / to-fp32 (zero_to_fp32) / "
                    "to-universal (ds_to_universal)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("inspect")
    p.add_argument("ckpt_dir")
    p.add_argument("--tag", default=None)

    p = sub.add_parser("to-fp32")
    p.add_argument("ckpt_dir")
    p.add_argument("output", help="output .npz path")
    p.add_argument("--tag", default=None)

    p = sub.add_parser("to-universal")
    p.add_argument("ckpt_dir")
    p.add_argument("output", help="output directory")
    p.add_argument("--tag", default=None)

    args = ap.parse_args(argv)
    if args.cmd == "inspect":
        print(json.dumps(inspect_checkpoint(args.ckpt_dir, args.tag), indent=2))
    elif args.cmd == "to-fp32":
        convert_to_fp32(args.ckpt_dir, args.output, args.tag)
    elif args.cmd == "to-universal":
        convert_to_universal(args.ckpt_dir, args.output, args.tag)
    return 0


if __name__ == "__main__":
    sys.exit(main())
