"""Universal / offline checkpoint tools.

Covers the reference's offline checkpoint machinery:

  * ``get_fp32_state_dict_from_checkpoint`` / ``convert_to_fp32`` —
    ``zero_to_fp32.py`` analog (deepspeed/utils/zero_to_fp32.py):
    consolidate a (possibly topology-sharded) engine checkpoint into a
    single host fp32 state dict, without needing a device mesh or a
    running cluster. The reference stitches flat dp-rank partitions with
    offset arithmetic; orbax stores *global* arrays, so consolidation is
    just a host restore of the master tree.
  * ``convert_to_universal`` — ``ds_to_universal.py`` analog
    (deepspeed/checkpoint/ds_to_universal.py:121-249): explode the
    checkpoint into one file per parameter (fp32 master + optimizer
    moments) so any future topology/zero-stage/framework can consume it.
  * ``load_universal`` — ``load_universal_checkpoint`` analog
    (runtime/zero/stage*.py + universal_checkpoint.py:99): map a
    universal dir back onto a live engine with the *current* sharding
    plan (resharding on load).
  * ``inspect_checkpoint`` + the ``dstpu-ckpt`` CLI.

On-disk universal layout (one dir per tree path, '.'-joined):

    <out>/universal/<param-path>/fp32.npy
    <out>/universal/<param-path>/<moment-name>.npy   (exp_avg, exp_avg_sq, ...)
    <out>/universal/metadata.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.checkpoint.state import (LATEST_FILE, METADATA_FILE,
                                            STATE_DIR)

UNIVERSAL_DIR = "universal"
SEP = "."

# optax moment field → the reference's torch optimizer-state name
# (universal checkpoints use the torch names so both frameworks can consume
# them; the inverse mapping lives in utils/tensor_fragment.py)
MOMENT_NAME_MAP = {"mu": "exp_avg", "m": "exp_avg",
                   "nu": "exp_avg_sq", "v": "exp_avg_sq",
                   "trace": "momentum"}
MOMENT_KEYS = tuple(MOMENT_NAME_MAP)


# ----------------------------------------------------------------------
# host-side restore
# ----------------------------------------------------------------------
def _resolve_tag(ckpt_root: str, tag: Optional[str]) -> str:
    if tag is None:
        latest = os.path.join(ckpt_root, LATEST_FILE)
        if not os.path.exists(latest):
            raise FileNotFoundError(
                f"no '{LATEST_FILE}' file in {ckpt_root}; pass an explicit tag")
        with open(latest) as f:
            tag = f.read().strip()
    return str(tag)


def _restore_host(ckpt_root: str, tag: Optional[str]
                  ) -> Tuple[Dict[str, Any], Dict[str, Any], str]:
    """Restore the saved tree as host numpy arrays + metadata."""
    import orbax.checkpoint as ocp

    tag = _resolve_tag(ckpt_root, tag)
    ckpt_dir = os.path.join(os.path.abspath(ckpt_root), tag)
    meta = {}
    meta_path = os.path.join(ckpt_dir, METADATA_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    with ocp.StandardCheckpointer() as ckptr:
        state = ckptr.restore(os.path.join(ckpt_dir, STATE_DIR))
    return state, meta, ckpt_dir


def _is_namedtuple(x) -> bool:
    return isinstance(x, tuple) and hasattr(x, "_fields")


def _flatten(tree, prefix="") -> Dict[str, np.ndarray]:
    """Flatten with '.'-joined keys. Namedtuples (optax states) flatten by
    FIELD NAME so live-engine trees and orbax-restored trees (which come
    back as field-name dicts) produce identical keys — the moment-name
    mapping below depends on this."""
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{SEP}{k}" if prefix else str(k)))
    elif _is_namedtuple(tree):
        for name, v in zip(tree._fields, tree):
            out.update(_flatten(v, f"{prefix}{SEP}{name}" if prefix else name))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{SEP}{i}" if prefix else str(i)))
    elif tree is None:
        pass
    else:
        out[prefix] = np.asarray(tree)
    return out


# ----------------------------------------------------------------------
# zero_to_fp32 analog
# ----------------------------------------------------------------------
def get_fp32_state_dict_from_checkpoint(ckpt_root: str,
                                        tag: Optional[str] = None
                                        ) -> Dict[str, np.ndarray]:
    """Single consolidated fp32 param dict (reference
    zero_to_fp32.py get_fp32_state_dict_from_zero_checkpoint)."""
    state, _meta, _dir = _restore_host(ckpt_root, tag)
    # prefer fp32 masters (exact); else cast the compute-dtype params
    src = state.get("opt_master") or state["params"]
    return {k: np.asarray(v, dtype=np.float32)
            for k, v in _flatten(src).items()}


def convert_to_fp32(ckpt_root: str, out_path: str,
                    tag: Optional[str] = None) -> str:
    """Write the consolidated fp32 dict as one .npz (zero_to_fp32 CLI)."""
    sd = get_fp32_state_dict_from_checkpoint(ckpt_root, tag)
    out_path = os.path.abspath(out_path)
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    tmp = out_path + ".tmp.npz"
    np.savez(tmp, **sd)
    os.replace(tmp, out_path)
    total = sum(v.size for v in sd.values())
    print(f"wrote {len(sd)} tensors / {total/1e6:.1f}M fp32 params -> {out_path}")
    return out_path


# ----------------------------------------------------------------------
# ds_to_universal analog
# ----------------------------------------------------------------------
def convert_to_universal(ckpt_root: str, out_dir: str,
                         tag: Optional[str] = None) -> str:
    """Explode an engine checkpoint into per-parameter files
    (reference ds_to_universal.py main: extract → merge → save)."""
    state, meta, _dir = _restore_host(ckpt_root, tag)
    out = os.path.join(os.path.abspath(out_dir), UNIVERSAL_DIR)
    os.makedirs(out, exist_ok=True)

    masters = _flatten(state.get("opt_master") or state["params"])
    moments: Dict[str, Dict[str, np.ndarray]] = {}
    # optax inner state: a tuple of stage states, each possibly holding
    # mu/nu/trace trees shaped like the params
    inner = state.get("opt_inner")
    if inner is not None:
        flat_inner = _flatten(inner)
        for key, arr in flat_inner.items():
            # key like "0.mu.<param-path>" — map moment-name per param
            parts = key.split(SEP)
            for i, p in enumerate(parts):
                if p in MOMENT_KEYS:
                    param_path = SEP.join(parts[i + 1:])
                    name = MOMENT_NAME_MAP[p]
                    if param_path in masters and \
                            arr.shape == masters[param_path].shape:
                        moments.setdefault(param_path, {})[name] = arr
                    break

    manifest = {}
    for path, arr in masters.items():
        pdir = os.path.join(out, path)
        os.makedirs(pdir, exist_ok=True)
        np.save(os.path.join(pdir, "fp32.npy"),
                np.asarray(arr, dtype=np.float32))
        entry = {"shape": list(arr.shape), "dtype": "float32",
                 "moments": sorted(moments.get(path, {}))}
        for name, m in moments.get(path, {}).items():
            np.save(os.path.join(pdir, f"{name}.npy"),
                    np.asarray(m, dtype=np.float32))
        manifest[path] = entry

    uni_meta = {
        "source_tag": meta.get("tag"),
        "global_steps": meta.get("global_steps"),
        "step_count": int(np.asarray(state.get("step_count", 0))),
        "source_mesh_shape": meta.get("mesh_shape"),
        "zero_stage": meta.get("zero_stage"),
        "params": manifest,
    }
    with open(os.path.join(out, METADATA_FILE), "w") as f:
        json.dump(uni_meta, f, indent=2)
    print(f"wrote universal checkpoint ({len(manifest)} params) -> {out}")
    return out


def _map_with_paths(tree, fn, prefix=""):
    """Structure-preserving map of ``fn(path, leaf)`` (dicts, namedtuples,
    lists/tuples — same path scheme as _flatten)."""
    if isinstance(tree, dict):
        return {k: _map_with_paths(v, fn,
                                   f"{prefix}{SEP}{k}" if prefix else str(k))
                for k, v in tree.items()}
    if _is_namedtuple(tree):
        vals = [_map_with_paths(v, fn,
                                f"{prefix}{SEP}{n}" if prefix else str(n))
                for n, v in zip(tree._fields, tree)]
        return type(tree)(*vals)
    if isinstance(tree, (list, tuple)):
        vals = [_map_with_paths(v, fn,
                                f"{prefix}{SEP}{i}" if prefix else str(i))
                for i, v in enumerate(tree)]
        return tuple(vals) if isinstance(tree, tuple) else vals
    if tree is None:
        return None
    return fn(prefix, tree)


def _put_like(host_arr: np.ndarray, like) -> Any:
    """Place a host array with ``like``'s sharding + dtype. Multi-process
    safe: every process holds the full array (read from shared storage),
    and make_array_from_callback assembles only this process's addressable
    shards — device_put of a cross-process global array is invalid in
    multi-controller JAX, and np.asarray of one would be too.
    """
    import jax

    arr = np.asarray(host_arr, dtype=np.dtype(like.dtype))
    sharding = like.sharding
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def load_universal(engine, universal_dir: str,
                   load_optimizer_states: bool = True):
    """Map a universal dir onto a live engine with its current sharding
    plan (reference load_universal_checkpoint; universal_checkpoint.py:99).

    Every param found in the dir is loaded (resharded onto the engine's
    target sharding); missing params keep their values. Works on
    multi-host meshes: file contents are read by every process and placed
    shard-by-shard, existing device arrays are never pulled to host.
    """
    import jax

    root = os.path.abspath(universal_dir)
    if os.path.basename(root) != UNIVERSAL_DIR and \
            os.path.isdir(os.path.join(root, UNIVERSAL_DIR)):
        root = os.path.join(root, UNIVERSAL_DIR)
    with open(os.path.join(root, METADATA_FILE)) as f:
        meta = json.load(f)

    flat: Dict[str, np.ndarray] = {}
    for path in meta["params"]:
        flat[path] = np.load(os.path.join(root, path, "fp32.npy"))

    if engine.opt_state is not None and load_optimizer_states:
        # fp32 masters: exact restore, then recompute compute-dtype params
        def restore_master(path, leaf):
            if path in flat and flat[path].shape == leaf.shape:
                return _put_like(flat[path], leaf)
            return leaf

        new_master = _map_with_paths(engine.opt_state.master, restore_master)
        step_count = meta.get("step_count")

        def restore_inner(path, leaf):
            parts = path.split(SEP)
            # optimizer step counters resume at the source run's step, or
            # Adam bias correction restarts from scratch
            if parts[-1] == "count" and getattr(leaf, "ndim", None) == 0 \
                    and step_count is not None:
                return _put_like(np.asarray(step_count), leaf)
            for i, p in enumerate(parts):
                if p in MOMENT_KEYS:
                    param_path = SEP.join(parts[i + 1:])
                    name = MOMENT_NAME_MAP[p]
                    f = os.path.join(root, param_path, f"{name}.npy")
                    if os.path.exists(f):
                        arr = np.load(f)
                        if arr.shape == tuple(leaf.shape):
                            return _put_like(arr, leaf)
                    break
            return leaf

        new_inner = _map_with_paths(engine.opt_state.inner, restore_inner)
        from deepspeed_tpu.runtime.optimizer import MixedPrecisionState

        engine.opt_state = MixedPrecisionState(master=new_master,
                                               inner=new_inner)
        cdt = engine.compute_dtype
        engine.params = jax.jit(
            lambda m: jax.tree.map(lambda x: x.astype(cdt), m),
            out_shardings=engine._param_shardings)(new_master)
    else:
        def restore_param(path, leaf):
            if path in flat and flat[path].shape == tuple(leaf.shape):
                return _put_like(flat[path], leaf)
            return leaf

        engine.params = _map_with_paths(engine.params, restore_param)
        if getattr(engine, "_offload", None) is not None:
            # offload engines keep the fp32 masters on host — re-seed them
            # from the restored params or the next step's master→param sync
            # would silently roll the model back (same hazard the regular
            # load path guards in state.py)
            engine._offload.reinit_masters(
                engine._jit_to_opt_sharding(jax.tree.map(
                    lambda x: x.astype("float32"), engine.params)))
            if load_optimizer_states:
                from deepspeed_tpu.utils.logging import logger

                logger.warning(
                    "load_universal: offload-engine optimizer moments are "
                    "not mapped from the universal dir (masters re-seeded "
                    "from params, moments reset)")

    step = meta.get("step_count")
    if step is not None:
        engine.step_count = _put_like(np.asarray(int(step), np.int32),
                                      engine.step_count)
        engine.global_steps = int(meta.get("global_steps") or step)
    return engine


# ----------------------------------------------------------------------
# inspection + CLI
# ----------------------------------------------------------------------
def inspect_checkpoint(ckpt_root: str, tag: Optional[str] = None) -> Dict:
    """Metadata-only: reads orbax tree metadata (shapes/dtypes), never the
    tensor payload — inspecting a multi-B-param checkpoint stays cheap."""
    import orbax.checkpoint as ocp

    tag = _resolve_tag(ckpt_root, tag)
    ckpt_dir = os.path.join(os.path.abspath(ckpt_root), tag)
    meta = {}
    meta_path = os.path.join(ckpt_dir, METADATA_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    with ocp.PyTreeCheckpointer() as ckptr:
        md = ckptr.metadata(os.path.join(ckpt_dir, STATE_DIR))
    item = getattr(md, "item_metadata", None)
    tree = getattr(item, "tree", None) or item or md
    shapes = {k: v for k, v in _flatten_meta(tree).items()}
    param_shapes = {k: v for k, v in shapes.items()
                    if k.split(SEP)[0] == "params"}
    n_params = sum(int(np.prod(s)) for s in param_shapes.values())
    return {
        "dir": ckpt_dir,
        "tag": meta.get("tag"),
        "global_steps": meta.get("global_steps"),
        "mesh_shape": meta.get("mesh_shape"),
        "zero_stage": meta.get("zero_stage"),
        "n_tensors": len(param_shapes),
        "n_params": n_params,
        "has_optimizer_state": any(k.split(SEP)[0] == "opt_master"
                                   for k in shapes),
    }


def _flatten_meta(tree, prefix="") -> Dict[str, Tuple[int, ...]]:
    """Flatten an orbax metadata tree to {path: shape}."""
    out: Dict[str, Tuple[int, ...]] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_meta(v, f"{prefix}{SEP}{k}" if prefix else str(k)))
    elif isinstance(tree, (list, tuple)) and not hasattr(tree, "shape"):
        for i, v in enumerate(tree):
            out.update(_flatten_meta(v, f"{prefix}{SEP}{i}" if prefix else str(i)))
    elif tree is None:
        pass
    else:
        out[prefix] = tuple(getattr(tree, "shape", ()) or ())
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="dstpu-ckpt",
        description="checkpoint tools: inspect / to-fp32 (zero_to_fp32) / "
                    "to-universal (ds_to_universal)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("inspect")
    p.add_argument("ckpt_dir")
    p.add_argument("--tag", default=None)

    p = sub.add_parser("to-fp32")
    p.add_argument("ckpt_dir")
    p.add_argument("output", help="output .npz path")
    p.add_argument("--tag", default=None)

    p = sub.add_parser("to-universal")
    p.add_argument("ckpt_dir")
    p.add_argument("output", help="output directory")
    p.add_argument("--tag", default=None)

    args = ap.parse_args(argv)
    if args.cmd == "inspect":
        print(json.dumps(inspect_checkpoint(args.ckpt_dir, args.tag), indent=2))
    elif args.cmd == "to-fp32":
        convert_to_fp32(args.ckpt_dir, args.output, args.tag)
    elif args.cmd == "to-universal":
        convert_to_universal(args.ckpt_dir, args.output, args.tag)
    return 0


if __name__ == "__main__":
    sys.exit(main())
