from deepspeed_tpu.checkpoint.state import CheckpointIO  # noqa: F401
from deepspeed_tpu.checkpoint.universal import (  # noqa: F401
    convert_to_fp32, convert_to_universal,
    get_fp32_state_dict_from_checkpoint, inspect_checkpoint, load_universal)
