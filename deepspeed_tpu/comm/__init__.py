from deepspeed_tpu.comm.comm import *  # noqa: F401,F403
from deepspeed_tpu.comm import comm  # noqa: F401
