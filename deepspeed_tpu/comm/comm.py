"""Communication facade: named-axis collectives over ICI/DCN.

TPU-native analog of ``deepspeed.comm`` (reference: deepspeed/comm/comm.py —
the torch.distributed-shaped module API at :227-682, ``init_distributed``
:792, ``timed_op`` wrappers :106). Three deltas from the reference design:

  1. There is no backend zoo (NCCL/gloo/CCL/...) — XLA emits the collectives
     for the platform; the "backend" is the compiler. Capability probes like
     ``has_all_gather_into_tensor`` become trivially true.
  2. Collectives are *named-axis* ops usable inside jit/shard_map bodies
     (they wrap ``jax.lax`` primitives). Outside jit, GSPMD usually inserts
     them from sharding annotations and user code never calls these.
  3. Per-op logging happens at trace time (see utils/comms_logging.py),
     because timing individual ops inside a compiled program from Python is
     meaningless.

``init_distributed`` performs the multi-host rendezvous
(``jax.distributed.initialize``), the analog of joining the job-wide
process group the reference launcher creates (comm/comm.py:792 →
torch.distributed.init_process_group).
"""

from __future__ import annotations

import os
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.utils.comms_logging import get_comms_logger
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils import jaxcompat

__all__ = [
    "init_distributed", "is_initialized", "get_world_size", "get_rank",
    "get_local_rank", "get_process_count", "barrier",
    "assert_same_across_processes", "any_process",
    "has_all_gather_into_tensor", "has_reduce_scatter_tensor",
    "has_coalescing_manager", "all_reduce", "all_gather", "reduce_scatter",
    "all_to_all", "ppermute", "broadcast", "axis_index", "axis_size",
    "traced_span", "configure", "log_summary", "get_retry_policy",
]

_INITIALIZED = False

# -- control-plane health (resilience block; docs/resilience.md) -------------
# A RetryPolicy bounds the process-level ops a wedged peer turns into a
# silent fleet-wide hang: rendezvous init, barrier, cross-process asserts.
# With no `resilience` config applied the default policy has no timeouts
# and every op is a plain passthrough.
_POLICY = None


def get_retry_policy():
    """The active control-plane RetryPolicy (timeout-less until
    ``configure`` installs one from the ``resilience`` config block)."""
    global _POLICY
    if _POLICY is None:
        from deepspeed_tpu.resilience.policy import RetryPolicy

        _POLICY = RetryPolicy()
    return _POLICY


def _chaos_collective(op: str) -> None:
    """Chaos hook: lets DSTPU_CHAOS delay/fail the Kth control-plane op
    (injected ChaosCollectiveError propagates; everything else is inert)."""
    try:
        from deepspeed_tpu.resilience.chaos import get_chaos_injector

        inj = get_chaos_injector()
    except Exception:
        return
    if inj.armed:
        inj.on_collective(op)


def is_initialized() -> bool:
    return _INITIALIZED


def init_distributed(
    dist_backend: str = "xla",
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    timeout: Optional[int] = None,
    dist_init_required: Optional[bool] = None,
) -> None:
    """Join the multi-host rendezvous (analog of comm/comm.py:792).

    Single-host (or already-initialized) is a no-op. Multi-host parameters
    come from args or the standard env autodiscovery the reference performs
    (comm/comm.py:861-953): COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID,
    plus TPU pod metadata which jax.distributed discovers natively.
    """
    global _INITIALIZED
    if _INITIALIZED or dist_init_required is False:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS") or os.environ.get("JAX_COORDINATOR_ADDRESS")
    num_processes = num_processes or _env_int("NUM_PROCESSES")
    process_id = process_id if process_id is not None else _env_int("PROCESS_ID")
    policy = get_retry_policy()
    try:
        # Only rendezvous when multi-host is explicitly configured; never
        # infer from TPU_* env alone (single-host sandboxes set those).
        if coordinator_address or (num_processes or 0) > 1 or dist_init_required:
            # bounded by resilience.init_timeout_s: a peer that never
            # shows up at rendezvous becomes a typed CommTimeoutError
            # (transient exit code) instead of an indefinite hang
            policy.run(
                "init_distributed",
                lambda: jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                ),
                timeout_s=policy.init_timeout_s,
            )
            log_dist(
                f"initialized distributed runtime: {jax.process_count()} processes",
                ranks=[0],
            )
    except RuntimeError as e:
        from deepspeed_tpu.resilience.policy import CommTimeoutError

        if isinstance(e, CommTimeoutError):
            raise  # exhausted rendezvous deadline — not "already init'd"
        # already initialized by the launcher — fine
        logger.debug(f"jax.distributed.initialize skipped: {e}")
    _INITIALIZED = True


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v is not None else None


# -- world/rank queries (process granularity on TPU) ------------------------


def get_world_size(group: Any = None) -> int:
    """Total **device** count (the reference's world = one rank per device).

    NOTE the granularity split vs the reference: on TPU one controller
    process drives many devices, so there is no per-device Python rank.
    ``get_world_size`` is device-granular (matches comm-volume math);
    ``get_rank`` is process-granular (matches "who does host-side work").
    Reference-style ``rank == world_size - 1`` loops do not port; use
    mesh-axis logic (lax.axis_index) inside compiled code instead.
    """
    return jax.device_count()


def get_rank(group: Any = None) -> int:
    """Host **process** index (see granularity note on get_world_size)."""
    return jax.process_index()


def get_local_rank() -> int:
    return 0  # one controller process per host drives all local devices


def get_process_count() -> int:
    return jax.process_count()


def barrier(group: Any = None) -> None:
    """Cross-host barrier: tiny psum over all devices. Bounded by
    ``resilience.collective_timeout_s`` when configured — a peer that
    never arrives raises CommTimeoutError instead of hanging the host."""
    _chaos_collective("barrier")
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        get_retry_policy().run(
            "barrier",
            lambda: multihost_utils.sync_global_devices(
                "deepspeed_tpu.barrier"))


def assert_same_across_processes(name: str, values) -> None:
    """Fail loudly when a config-critical value diverges across hosts.

    Reference: ``assert_ints_same_as_other_ranks`` (runtime/zero/
    utils.py:106) and AutoEP's cross-rank payload digests
    (moe/ep_tp_dispatch.py:99) — multi-host divergence (mismatched
    configs, different checkpoints, skewed data pipelines) otherwise
    corrupts training silently. ``values`` is a scalar/sequence of ints
    (strings hash to ints); no-op on a single process.
    """
    _chaos_collective(f"assert_same:{name}")
    if jax.process_count() <= 1:
        return
    import numpy as np
    from jax.experimental import multihost_utils

    def canon(v):
        if isinstance(v, str):
            import zlib

            return zlib.crc32(v.encode())
        return int(v)

    if isinstance(values, (list, tuple)):
        local = np.asarray([canon(v) for v in values], np.int64)
    else:
        local = np.asarray([canon(values)], np.int64)
    # only the allgather runs under the deadline: the divergence check
    # below must raise its own RuntimeError, never a retried one
    gathered = np.asarray(get_retry_policy().run(
        f"assert_same:{name}",
        lambda: multihost_utils.process_allgather(local)))
    if not (gathered == gathered[0]).all():
        rows = {i: gathered[i].tolist() for i in range(gathered.shape[0])}
        raise RuntimeError(
            f"cross-process consistency check failed for {name!r}: "
            f"processes disagree — per-process values {rows}. All hosts "
            "must run identical configs/checkpoints (reference "
            "assert_ints_same_as_other_ranks, runtime/zero/utils.py:106)")


def any_process(value: bool) -> bool:
    """True when ANY process reports ``value`` truthy (collective; every
    process must call it — the companion to assert_same_across_processes
    for per-rank conditions like missing per-rank files, where one rank
    raising alone would leave its peers hung in the next collective)."""
    if jax.process_count() <= 1:
        return bool(value)
    import numpy as np
    from jax.experimental import multihost_utils

    gathered = np.asarray(get_retry_policy().run(
        "any_process",
        lambda: multihost_utils.process_allgather(
            np.asarray([int(bool(value))], np.int64))))
    return bool(gathered.any())


# -- capability probes (reference comm/comm.py:325,629) ---------------------


def has_all_gather_into_tensor() -> bool:
    return True


def has_reduce_scatter_tensor() -> bool:
    return True


def has_coalescing_manager() -> bool:
    return True  # XLA coalesces/fuses collectives during scheduling


# -- in-jit named-axis collectives ------------------------------------------
# These are usable inside shard_map/pjit bodies. `axis` is a mesh axis name
# or tuple of names. Each records traced bytes with the CommsLogger.


def _nbytes(x) -> int:
    aval = jax.core.get_aval(x) if not hasattr(x, "nbytes") else x
    try:
        return int(aval.nbytes)
    except Exception:
        import numpy as np

        return int(np.prod(aval.shape) * jnp.dtype(aval.dtype).itemsize)


class _traced_op:
    """Dispatch→completion span around one traced collective: records
    the comms logger at entry (byte accounting, unchanged) and appends
    ONE flight-recorder event stamped with the dispatch start plus a
    ``dur_ms`` field at exit — so chrome_trace.py renders each traced
    collective as a Perfetto "X" slice on the comm lane instead of an
    instant marker, and overlapping dispatches show as overlapping
    slices. These fire at trace time (timing executed collectives inside
    a compiled program from Python is meaningless); the span covers the
    primitive's trace-time dispatch, which is also what a hang dump
    needs: which collectives the wedged program contains, in order."""

    __slots__ = ("_op", "_nb", "_axis", "_t0")

    def __init__(self, op: str, x, axis, log_name=None):
        name = log_name or op
        self._op = name
        self._axis = str(axis)
        self._nb = None
        try:
            self._nb = _nbytes(x)
            get_comms_logger().record(op, self._nb, axis, log_name)
        except Exception:
            pass

    def __enter__(self):
        import time as _time

        self._t0 = _time.time()
        return self

    def __exit__(self, *exc):
        import time as _time

        try:
            from deepspeed_tpu.observability.flight_recorder import \
                get_flight_recorder

            rec = get_flight_recorder()
            if rec.enabled:
                rec._ring.append((self._t0, "collective", {
                    "op": self._op, "bytes": self._nb, "axis": self._axis,
                    "dur_ms": (_time.time() - self._t0) * 1e3}))
        except Exception:
            pass
        return False


def all_reduce(x, axis, op: str = "sum", log_name: Optional[str] = None):
    """lax.psum/pmean/pmax over a named mesh axis (reference all_reduce
    comm/comm.py:497)."""
    with _traced_op("all_reduce", x, axis, log_name):
        if op == "sum":
            return lax.psum(x, axis)
        if op in ("avg", "mean"):
            return lax.pmean(x, axis)
        if op == "max":
            return lax.pmax(x, axis)
        if op == "min":
            return lax.pmin(x, axis)
    raise ValueError(f"unsupported reduce op: {op}")


def all_gather(x, axis, *, tiled: bool = True, gather_dim: int = 0,
               log_name: Optional[str] = None):
    """all_gather_into_tensor analog (comm/comm.py:320)."""
    with _traced_op("all_gather", x, axis, log_name):
        return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x, axis, *, scatter_dim: int = 0, op: str = "sum",
                   log_name: Optional[str] = None):
    """reduce_scatter_tensor analog (comm/comm.py:257)."""
    with _traced_op("reduce_scatter", x, axis, log_name):
        out = lax.psum_scatter(x, axis, scatter_dimension=scatter_dim,
                               tiled=True)
        if op in ("avg", "mean"):
            out = out / jaxcompat.axis_size(axis)
        return out


def all_to_all(x, axis, *, split_dim: int, concat_dim: int,
               log_name: Optional[str] = None):
    """all_to_all_single analog (comm/comm.py:392); the Ulysses primitive."""
    with _traced_op("all_to_all", x, axis, log_name):
        return lax.all_to_all(x, axis, split_axis=split_dim,
                              concat_axis=concat_dim, tiled=True)


def ppermute(x, axis, perm, log_name: Optional[str] = None):
    """Point-to-point ring shift (the reference's p2p send/recv
    pipe/p2p.py:46,67 becomes a collective-permute on TPU)."""
    with _traced_op("ppermute", x, axis, log_name):
        return lax.ppermute(x, axis, perm)


def traced_span(op: str, x, axis, log_name: Optional[str] = None):
    """Context manager giving GSPMD-implicit collectives the same byte
    accounting + flight-recorder span the explicit wrappers above get.

    Some collectives are not dispatched as lax primitives but emitted by
    the partitioner from sharding constraints (Ulysses's all-to-alls in
    parallel/ulysses.py). Wrap the constraint in ``traced_span`` so the
    collective still lands in the comms logger and on the chrome-trace
    collective lane::

        with comm.traced_span("all_to_all", q, "sp", "ulysses_qkv"):
            q = _constrain(q, head_sharded_spec)
    """
    return _traced_op(op, x, axis, log_name)


def broadcast(x, axis, root: int = 0, log_name: Optional[str] = None):
    """Broadcast from `root` along a named axis (comm/comm.py:227)."""
    with _traced_op("broadcast", x, axis, log_name):
        idx = lax.axis_index(axis)
        masked = jnp.where(idx == root, x, jnp.zeros_like(x))
        return lax.psum(masked, axis)


def axis_index(axis):
    return lax.axis_index(axis)


def axis_size(axis):
    return jaxcompat.axis_size(axis)


def configure(config=None) -> None:
    """Wire the comms logger (reference dist.configure engine.py:323)
    and install the control-plane RetryPolicy from the ``resilience``
    config block."""
    global _POLICY
    if config is not None:
        get_comms_logger().configure(config.comms_logger)
        rcfg = getattr(config, "resilience", None)
        if rcfg is not None and getattr(rcfg, "enabled", True):
            from deepspeed_tpu.resilience.policy import RetryPolicy

            _POLICY = RetryPolicy.from_config(rcfg)


def log_summary(show_straggler: bool = False) -> str:
    return get_comms_logger().log_summary()
